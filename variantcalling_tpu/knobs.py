"""Typed registry of every ``VCTPU_*`` environment knob.

PR 2 (engine contract) and PR 3 (forest strategies) each ended with the
same lesson: an env knob that is parsed ad hoc at its point of use is a
determinism hole — a malformed value surfaces as a mid-run traceback on
one engine and a silent fallback on another, and a typo
(``VCTPU_FOERST_STRATEGY=wide``) configures nothing at all without a
word of warning. This module is the fix, mechanically enforced by the
``vctpu-lint`` VCT001 checker (docs/static_analysis.md): **every**
``VCTPU_*`` read in the tree goes through this registry, and this module
is the only file allowed to touch ``os.environ`` for a ``VCTPU_`` key.

Contract (the PR 3 ``validate_strategy_env`` rule, extended to every
knob):

- each knob declares its name, type, default, bounds/choices and help in
  :data:`REGISTRY`;
- parsing happens in ONE place (:func:`get`); a malformed value raises
  :class:`~variantcalling_tpu.engine.EngineError` — CLI exit code 2 on
  every engine and every forest strategy, never a mid-run ``ValueError``
  from inside a jit trace (``filter_variants.run`` calls
  :func:`validate_all` up front);
- unknown ``VCTPU_*`` variables are reported at CLI startup with a
  closest-match suggestion (:func:`warn_unknown_env`);
- ``vctpu knobs`` dumps the resolved value and source of every knob, and
  the filter pipeline records the explicitly-set scoring knobs in the
  output VCF header next to ``##vctpu_engine=`` (:func:`header_line`).

Booleans accept ``1/true/yes/on`` and ``0/false/no/off`` (case
insensitive); a set-but-empty variable means "unset" except for ``str``
knobs, where the empty string is meaningful (``VCTPU_COMPILE_CACHE=""``
disables the cache).
"""

from __future__ import annotations

import contextvars
import difflib
import os
from dataclasses import dataclass
from typing import Any

from variantcalling_tpu import logger

_TRUE = ("1", "true", "yes", "on")
_FALSE = ("0", "false", "no", "off")


def _config_error(msg: str) -> Exception:
    # EngineError is the one exception class the CLIs map to exit code 2;
    # imported lazily because engine.py imports this module at its top.
    from variantcalling_tpu.engine import EngineError

    return EngineError(msg)


@dataclass(frozen=True)
class Knob:
    """One declared ``VCTPU_*`` environment knob."""

    name: str  # full env name, e.g. "VCTPU_THREADS"
    kind: str  # "bool" | "int" | "float" | "str" | "enum"
    default: Any  # typed default when unset (None = no value)
    help: str
    choices: tuple[str, ...] | None = None  # enum values
    label: str | None = None  # enum error noun ("engine", "forest strategy")
    positive: bool = False  # int must be > 0
    minimum: float | None = None  # inclusive numeric lower bound
    in_header: bool = False  # recorded in ##vctpu_knobs= when env-set


def _k(*args, **kwargs) -> Knob:
    return Knob(*args, **kwargs)


#: Every knob the framework reads. Keep alphabetical within each group.
REGISTRY: dict[str, Knob] = {k.name: k for k in (
    # -- engine / scoring configuration (recorded via their own header
    #    lines: ##vctpu_engine= / ##vctpu_forest_strategy=) --------------
    _k("VCTPU_ENGINE", "enum", "auto",
       "scoring engine contract: auto|native|jit (docs/robustness.md)",
       choices=("auto", "native", "jit"), label="engine"),
    _k("VCTPU_REQUIRE_NATIVE", "bool", False,
       "fail loudly (exit 2) when the native scoring engine cannot load"),
    _k("VCTPU_NATIVE_FOREST", "bool", True,
       "legacy spelling of VCTPU_ENGINE=jit when 0 (predates engine.py)"),
    _k("VCTPU_NO_NATIVE", "bool", False,
       "disable the native C++ library entirely (build/load returns None)"),
    _k("VCTPU_FOREST_STRATEGY", "enum", "auto",
       "forest inference strategy: auto|gather|gemm|wide|pallas "
       "(docs/models.md)",
       choices=("auto", "gather", "gemm", "wide", "pallas"),
       label="forest strategy"),
    _k("VCTPU_MODEL_FAMILY", "enum", "auto",
       "scoring model family: auto|forest|dan — explicit request fails "
       "loudly when the loaded model is another family (docs/models.md)",
       choices=("auto", "forest", "dan"),
       label="model family"),
    _k("VCTPU_PALLAS", "bool", True,
       "allow the pallas wide-block kernel in strategy auto-resolution",
       in_header=True),
    _k("VCTPU_WIDE_CHUNK", "int", None,
       "N-chunk of the wide-contraction driver (bounds the decision "
       "tensor); default models/forest.WIDE_CHUNK", positive=True,
       in_header=True),
    _k("VCTPU_WIDE_BLOCK", "int", None,
       "trees per block-diagonal routing block; default fills the "
       "128-lane MXU", positive=True, in_header=True),
    _k("VCTPU_NATIVE_GBT", "bool", True,
       "allow the native partitioned-sample GBT trainer on CPU fits"),
    _k("VCTPU_NATIVE_FUSED", "bool", True,
       "native engine: score each chunk via the single fused "
       "parse->featurize->walk native call; 0 selects the unfused "
       "byte-parity reference path (docs/perf_notes.md)"),
    _k("VCTPU_MESH_DEVICES", "int", None,
       "data-parallel mesh size for XLA scoring (shard_map over dp); 1 "
       "pins single-device, default auto — 1 on cpu, every local device "
       "on accelerators (docs/streaming_executor.md)", positive=True),
    _k("VCTPU_MESH_MEGABATCH_ROWS", "int", None,
       "rows per mesh scoring megabatch in the streaming executor; "
       "default 16384 x mesh devices", positive=True),
    _k("VCTPU_MESH_OVERLAP", "bool", True,
       "overlap megabatch packing with the in-flight scoring dispatch "
       "(one group in flight on a dedicated dispatch worker); 0 keeps "
       "the synchronous pack-then-score loop "
       "(docs/streaming_executor.md)"),
    # -- streaming executor / parallel host pipeline --------------------
    _k("VCTPU_THREADS", "int", None,
       "host pipeline threads; 1 selects the serial path; default cpu "
       "count", positive=True),
    _k("VCTPU_STREAM", "bool", True,
       "allow the streaming (chunked, overlapped) filter executor"),
    _k("VCTPU_STREAM_CHUNK_BYTES", "int", 8 << 20,
       "bytes of VCF text per streaming pipeline item", positive=True),
    _k("VCTPU_IO_THREADS", "int", None,
       "host-IO worker pool size (sharded BGZF inflate, parallel chunk "
       "parse, writeback block compress); 1 disables parallel IO; "
       "default cpu count", positive=True),
    _k("VCTPU_IO_SHARD_BYTES", "int", 4 << 20,
       "decompressed bytes per parallel BGZF inflate shard "
       "(docs/streaming_executor.md)", positive=True),
    _k("VCTPU_NATIVE_THREADS", "int", None,
       "native engine kernel fan-out cap (C++ for_shards; read by the "
       "native library directly); default hardware concurrency",
       positive=True),
    _k("VCTPU_STAGE_TIMEOUT_S", "float", 900.0,
       "streaming-stage watchdog deadline in seconds (0 disables)",
       minimum=0.0),
    _k("VCTPU_IO_RETRIES", "int", 2,
       "bounded retries for transient ingest/writeback IO errors",
       minimum=0),
    _k("VCTPU_IO_BACKOFF_S", "float", 0.05,
       "initial retry backoff in seconds (doubles per attempt, plus "
       "bounded deterministic per-worker jitter)", minimum=0.0),
    _k("VCTPU_CHUNK_RETRIES", "int", 1,
       "bounded re-dispatches of a failed streaming chunk / megabatch "
       "before the failure is final (recovery ladder, "
       "docs/robustness.md); 0 fails on the first strike", minimum=0),
    _k("VCTPU_QUARANTINE", "bool", False,
       "divert deterministically-failing chunks to a <out>.quarantine "
       "sidecar instead of failing the run (OPT-IN: changes which "
       "records reach the output; default fails loudly — "
       "docs/robustness.md recovery ladder)",
       # changes WHICH records reach the output => scoring-class
       # (knobs_contract.json): an artifact produced under quarantine
       # must say so in its ##vctpu_knobs= provenance header
       in_header=True),
    _k("VCTPU_RESUME", "bool", True,
       "resume interrupted plain-text runs from the chunk journal"),
    _k("VCTPU_RESUME_VERIFY", "enum", "last",
       "journal resume verification depth: last (spot-check the final "
       "chunk's CRC) or full (re-read and CRC-check every journaled "
       "chunk plus the header)", choices=("last", "full"),
       label="resume verification mode"),
    _k("VCTPU_JOURNAL_FSYNC", "bool", False,
       "fsync the partial output and journal after every committed "
       "chunk (durability over throughput; default relies on flush "
       "ordering only)"),
    # -- multi-host -----------------------------------------------------
    _k("VCTPU_COORDINATOR", "str", None,
       "host:port of rank 0 — presence turns any tool into one rank of "
       "a global mesh (docs/distributed.md)"),
    _k("VCTPU_NUM_PROCESSES", "int", None,
       "total ranks of a multi-host launch (jax.distributed) or of a "
       "rank-partitioned local pod run (docs/scaleout.md)", positive=True),
    _k("VCTPU_RANK", "int", None,
       "this process's rank in a rank-partitioned scale-out run "
       "(tools/podrun sets it; resolved BEFORE any jax init, so the "
       "local launcher needs no jax.distributed — docs/scaleout.md)",
       minimum=0),
    _k("VCTPU_PROCESS_ID", "int", None,
       "this rank's id in a multi-host launch", minimum=0),
    _k("VCTPU_SPAN", "str", None,
       "lo:hi:gen — this worker is one leased span of an elastic pod "
       "(absolute decompressed-byte targets + lease generation; "
       "tools/podrun --elastic sets it — docs/scaleout.md \"Elastic "
       "membership\")"),
    _k("VCTPU_AUTO_DISTRIBUTED", "bool", False,
       "initialize jax.distributed from the cluster environment (TPU "
       "pods)"),
    _k("VCTPU_ALL_RANKS_WRITE", "bool", False,
       "let every rank write its own output copy (default: rank 0 only)"),
    # -- caches / IO ----------------------------------------------------
    _k("VCTPU_CACHE", "bool", False,
       "content-addressed chunk-result cache: replay rendered chunk "
       "bodies keyed on (input span CRC, scoring identity) instead of "
       "recomputing them (OPT-IN; byte-identical output either way — "
       "docs/caching.md)"),
    _k("VCTPU_CACHE_DIR", "str", "",
       "chunk-result cache directory (default ~/.cache/vctpu/chunks; "
       "one store shared across ranks/spans — keys are "
       "partition-agnostic)"),
    _k("VCTPU_CACHE_MAX_MB", "int", 512,
       "chunk-result cache size bound in MiB (LRU eviction; bounds the "
       "on-disk store and the serve daemon's in-memory warm index "
       "separately)", positive=True),
    _k("VCTPU_COMPILE_CACHE", "str", None,
       "persistent XLA compilation cache dir; empty string disables; "
       "default ~/.cache/vctpu/xla"),
    _k("VCTPU_GENOME_CACHE", "bool", True,
       "persist the encoded genome as a .venc sidecar and memmap hits"),
    _k("VCTPU_GENOME_CACHE_DIR", "str", "",
       "directory for .venc sidecars (default: next to the FASTA)"),
    _k("VCTPU_FASTA_CACHE_BYTES", "int", 4 << 30,
       "byte budget of the in-memory encoded-contig cache (0 disables)",
       minimum=0),
    _k("VCTPU_CLOUD_TIMEOUT", "int", 600,
       "seconds before a cloud-CLI localization attempt is killed",
       positive=True),
    _k("VCTPU_SUBPROC_TIMEOUT_S", "int", 3600,
       "timeout for external tool subprocesses (beagle, …) — VCT005: no "
       "subprocess runs unbounded", positive=True),
    # -- vctpu serve — the resident daemon (docs/serving.md) -----------
    _k("VCTPU_SERVE_HOST", "str", "127.0.0.1",
       "vctpu serve bind address (localhost only by design — the daemon "
       "is a host-local multiplexer, not an internet face)"),
    _k("VCTPU_SERVE_PORT", "int", 8844,
       "vctpu serve TCP port (0 = ephemeral, the chosen port lands in "
       "the --ready-file)", minimum=0),
    _k("VCTPU_SERVE_SOCKET", "str", "",
       "vctpu serve Unix-domain socket path (set -> AF_UNIX instead of "
       "TCP)"),
    _k("VCTPU_SERVE_MAX_INFLIGHT", "int", 2,
       "admission control: pipeline requests executing concurrently; "
       "further admitted requests wait in the bounded queue",
       positive=True),
    _k("VCTPU_SERVE_QUEUE_DEPTH", "int", 8,
       "admission control: requests allowed to WAIT for an execution "
       "slot; arrivals beyond it are shed with an explicit 503 "
       "(docs/serving.md admission/shed policy)", minimum=0),
    _k("VCTPU_SERVE_DEADLINE_S", "float", 300.0,
       "default per-request deadline in seconds (queue wait + "
       "execution); the request JSON's deadline_s overrides per "
       "request; expiry cancels the request at the next chunk boundary "
       "(0 disables)", minimum=0.0),
    _k("VCTPU_SERVE_DRAIN_S", "float", 60.0,
       "graceful-drain budget on SIGTERM/SIGINT: finish in-flight "
       "requests up to this many seconds while refusing new work, then "
       "exit", minimum=0.0),
    # -- vctpu serve --fabric — the scatter-gather router tier
    #    (docs/serving_fabric.md) ---------------------------------------
    _k("VCTPU_FABRIC_BACKENDS", "str", "",
       "comma-separated backend daemon addresses the router registers "
       "at startup (http://host:port, or a filesystem path for "
       "AF_UNIX); each must be a `vctpu serve --fabric-backend` daemon"),
    _k("VCTPU_FABRIC_HEARTBEAT_S", "float", 2.0,
       "router heartbeat period in seconds: each beat polls every "
       "registered backend's /v1/status (rolling-SLO series) and "
       "/v1/metrics (prom text, cpu-ledger series included when the "
       "backend samples them)", minimum=0.05),
    _k("VCTPU_FABRIC_DEAD_AFTER", "int", 3,
       "consecutive failed heartbeats before the router marks a backend "
       "dead (stops placing spans on it; membership event emitted)",
       positive=True),
    _k("VCTPU_FABRIC_QUOTA", "int", 4,
       "per-principal concurrent-request quota at the front door; "
       "arrivals beyond it get 429 with Retry-After (bearer tokens map "
       "requests to principals — VCTPU_FABRIC_TOKENS)", positive=True),
    _k("VCTPU_FABRIC_TOKENS", "str", "",
       "bearer-token auth table for the front door: "
       "'token:principal,token2:principal2'; empty string disables auth "
       "(every request is the 'anonymous' principal)"),
    _k("VCTPU_FABRIC_STREAM_CHUNK_BYTES", "int", 1 << 20,
       "chunked-transfer frame size for fabric body streaming (request "
       "upload spooling and response download)", positive=True),
    _k("VCTPU_FABRIC_SPAN_ATTEMPTS", "int", 2,
       "placement attempts per span before the whole request fails with "
       "a distinct backend_lost status (each re-span bumps the lease "
       "generation and lands on a different live backend)",
       positive=True),
    # -- diagnostics / test harness ------------------------------------
    _k("VCTPU_OBS", "bool", False,
       "record run telemetry (manifest + metrics + event log) to an obs "
       "JSONL sidecar (docs/observability.md)"),
    _k("VCTPU_OBS_PATH", "str", "",
       "obs run-log path override; default <output_file>.obs.jsonl"),
    _k("VCTPU_OBS_PROFILE", "bool", True,
       "obs v2 attribution when VCTPU_OBS=1: per-stage work/wait "
       "profile, RSS/CPU watermark sampler, runtime cost_analysis "
       "(docs/observability.md)"),
    _k("VCTPU_OBS_SAMPLE_S", "float", 0.05,
       "resource-watermark sampler interval in seconds", minimum=0.001),
    _k("VCTPU_OBS_CPUPROF", "bool", False,
       "obs v3 continuous CPU sampling profiler when VCTPU_OBS=1: "
       "whole-process stack samples + per-thread CPU clocks folded into "
       "the sample event stream (vctpu obs flame / cpuledger; "
       "docs/observability.md)"),
    _k("VCTPU_OBS_CPUPROF_HZ", "float", 7.0,
       "continuous-profiler sampling rate in Hz; the conservative "
       "default fits the <=2% overhead budget on a saturated 2-core "
       "host (every tick holds the GIL briefly) — raise it on hosts "
       "with spare cores for finer flames", minimum=1.0),
    _k("VCTPU_OBS_TAIL_POLL_S", "float", 1.0,
       "vctpu obs tail --follow poll interval in seconds "
       "(--interval-s overrides per invocation)", minimum=0.01),
    _k("VCTPU_OBS_JAXPROF", "bool", False,
       "capture a jax.profiler device trace (<run log>.jaxprof/) "
       "alongside the obs stream for side-by-side Perfetto loading"),
    _k("VCTPU_OBS_TRACE", "bool", True,
       "causal chunk tracing when VCTPU_OBS=1: per-chunk trace ids, "
       "per-stage trace spans with parent links (the walkable DAG "
       "vctpu obs critical-path consumes); 0 opts out "
       "(docs/observability.md)"),
    _k("VCTPU_OBS_SNAPSHOT_S", "float", 10.0,
       "minimum seconds between periodic in-run metrics snapshots "
       "(kind=snapshot, emitted on the event-flush cadence; the live "
       "plane for vctpu obs tail/prom); 0 disables", minimum=0.0),
    _k("VCTPU_OBS_WINDOW_S", "float", 60.0,
       "rolling-window span of the windowed histogram quantiles "
       "(rolling p50/p95/p99 mean 'the last ~window', not all-of-run)",
       minimum=1.0),
    _k("VCTPU_OBS_MAX_MB", "int", None,
       "obs run-log size cap in MB: the stream rotates to .seg1/.seg2/"
       "... segments at the cap (readers merge segments transparently); "
       "unset = one unbounded file", positive=True),
    _k("VCTPU_OBS_PROM_FILE", "str", "",
       "Prometheus textfile-collector path: every periodic snapshot "
       "atomically rewrites this file with the text exposition "
       "(vctpu obs prom is the offline sibling)"),
    _k("VCTPU_BENCH_GATE", "bool", False,
       "run_tests.sh: run the opt-in bench regression gate stage "
       "(tools/bench_gate.py) before pytest"),
    _k("VCTPU_BENCH_BASELINE", "str", "",
       "bench_gate baseline JSON path; default: newest committed "
       "BENCH_r*.json"),
    _k("VCTPU_TRACE", "bool", False,
       "print every closed trace span at INFO level"),
    _k("VCTPU_FAULTS", "str", "",
       "fault-injection spec, e.g. io.chunk_read:2,pipeline.stage_hang@30 "
       "(utils/faults.py)"),
    _k("VCTPU_FLAKEHUNT", "bool", False,
       "run_tests.sh: repeat flakehunt-marked tests 5x after the main run"),
    _k("VCTPU_CHAOS", "bool", False,
       "run_tests.sh: run the opt-in chaos smoke stage (tools/chaoshunt, "
       "10 fixed seeds) after tier-0 lint"),
    _k("VCTPU_LOAD", "bool", False,
       "run_tests.sh: run the opt-in load×chaos smoke stage "
       "(tools/loadhunt, 10 fixed seeds against a real vctpu serve "
       "daemon — docs/serving.md)"),
    _k("VCTPU_SCALEOUT", "bool", False,
       "run_tests.sh: run the opt-in simulated multi-host stage (the "
       "2-process local launcher end-to-end on the cpu backend plus the "
       "multi-process system tests — docs/scaleout.md)"),
    _k("VCTPU_PROBE_INTERVAL", "int", 1800,
       "tools/tpu_probe.py polling interval in seconds", positive=True),
    _k("VCTPU_PROBE_HOURS", "float", 11.5,
       "tools/tpu_probe.py total probe-loop duration in hours",
       minimum=0.0),
)}


#: request/thread-scoped override layer (``knobs.scope``): an immutable
#: mapping of knob name -> raw string (or None == "mask the env: resolve
#: the declared default"), carried in a contextvar so two concurrent
#: ``vctpu serve`` requests can never observe each other's settings. The
#: executor propagates the submitting context into its worker pools
#: (parallel/pipeline.py), so the scope follows the request's work onto
#: pooled chunk bodies, stage threads and the mesh dispatch worker.
_SCOPE: contextvars.ContextVar[dict[str, str | None] | None] = \
    contextvars.ContextVar("vctpu_knob_scope", default=None)


class scope:
    """Layer raw knob overrides over the process registry for the
    current execution context (docs/serving.md "Per-request knobs").

    ``overrides`` maps registered knob names to raw strings (parsed by
    the registry's ONE parse point exactly as env text would be — a
    malformed value raises ``EngineError`` at the first read) or to
    ``None`` to mask an env setting back to the declared default.
    Scopes nest: an inner scope merges over the outer one; leaving a
    scope restores the previous layer exactly (contextvar token), so a
    scope can never leak into a sibling request. Unknown names raise
    ``KeyError`` at entry — a typo'd per-request knob is a per-request
    configuration error, never a silent no-op."""

    __slots__ = ("overrides", "_token")

    def __init__(self, overrides: dict[str, object] | None = None, **kw):
        merged: dict[str, object] = dict(overrides or {})
        merged.update(kw)
        for name in merged:
            if name not in REGISTRY:
                raise KeyError(f"{name} is not a registered VCTPU knob")
        self.overrides = {
            name: (None if value is None else str(value))
            for name, value in merged.items()
        }
        self._token = None

    def __enter__(self) -> "scope":
        base = _SCOPE.get()
        layered = dict(base) if base else {}
        layered.update(self.overrides)
        self._token = _SCOPE.set(layered)
        return self

    def __exit__(self, *exc) -> bool:
        _SCOPE.reset(self._token)
        self._token = None
        return False


def scoped(name: str) -> bool:
    """Is ``name`` overridden by the current context's scope layer?"""
    layer = _SCOPE.get()
    return layer is not None and name in layer


def raw(name: str) -> str | None:
    """The raw string a knob resolves from (None when unset): the
    context's scope layer first (``knobs.scope`` — per-request
    overrides), else the environment. This module is the single
    ``os.environ`` access point for ``VCTPU_*`` keys; callers that need
    the uninterpreted text (predictor-cache keys) use this instead of
    touching the environment themselves."""
    if name not in REGISTRY:
        raise KeyError(f"{name} is not a registered VCTPU knob")
    layer = _SCOPE.get()
    if layer is not None and name in layer:
        return layer[name]
    return os.environ.get(name)


def _parse(knob: Knob, raw_value: str) -> Any:
    text = raw_value.strip()
    if knob.kind == "str":
        return raw_value
    if not text:  # set-but-empty == unset for non-str knobs
        return knob.default
    if knob.kind == "bool":
        low = text.lower()
        if low in _TRUE:
            return True
        if low in _FALSE:
            return False
        raise _config_error(
            f"{knob.name}={raw_value!r} is not a valid boolean; use one of "
            f"{'/'.join(_TRUE)} or {'/'.join(_FALSE)}")
    if knob.kind == "enum":
        low = text.lower()
        if low not in knob.choices:
            noun = knob.label or knob.name
            raise _config_error(
                f"{knob.name}={low!r} is not a valid {noun}; choose one of "
                f"{'/'.join(knob.choices)}")
        return low
    if knob.kind == "int":
        try:
            value = int(text)
        except ValueError:
            value = None
        if knob.positive:
            if value is None or value <= 0:
                raise _config_error(
                    f"{knob.name}={raw_value!r} is not a positive integer")
        elif value is None:
            raise _config_error(
                f"{knob.name}={raw_value!r} is not an integer")
    elif knob.kind == "float":
        try:
            value = float(text)
        except ValueError:
            raise _config_error(
                f"{knob.name}={raw_value!r} is not a number") from None
    else:  # pragma: no cover — registry construction guards kinds
        raise _config_error(f"unknown knob kind {knob.kind!r} for {knob.name}")
    if knob.minimum is not None and value < knob.minimum:
        raise _config_error(
            f"{knob.name}={raw_value!r} must be >= {knob.minimum}")
    return value


def get(name: str) -> Any:
    """The typed, validated value of a registered knob (env beats the
    declared default). The ONE parse point: a malformed value raises
    ``EngineError`` here — exit code 2 at every CLI — regardless of
    which engine or strategy the run would have used."""
    knob = REGISTRY.get(name)
    if knob is None:
        raise KeyError(f"{name} is not a registered VCTPU knob")
    raw_value = raw(name)
    if raw_value is None:
        return knob.default
    return _parse(knob, raw_value)


def _typed(name: str, kinds: tuple[str, ...]) -> Any:
    knob = REGISTRY.get(name)
    if knob is None:
        raise KeyError(f"{name} is not a registered VCTPU knob")
    if knob.kind not in kinds:
        raise TypeError(f"{name} is a {knob.kind} knob, not {'/'.join(kinds)}")
    return get(name)


def get_bool(name: str) -> bool:
    return _typed(name, ("bool",))


def get_int(name: str) -> int | None:
    return _typed(name, ("int",))


def get_float(name: str) -> float:
    return _typed(name, ("float",))


def get_str(name: str) -> str | None:
    return _typed(name, ("str", "enum"))


def source(name: str) -> str:
    """Where the resolved value came from: ``"scope"`` (a
    ``knobs.scope`` override in the current context), ``"env"`` or
    ``"default"``."""
    if scoped(name):
        return "scope"
    return "env" if raw(name) is not None else "default"


def resolved() -> list[tuple[str, Any, str]]:
    """(name, typed value, source) for every registered knob, sorted.
    Raises on the first malformed value, like :func:`validate_all`."""
    return [(name, get(name), source(name)) for name in sorted(REGISTRY)]


def validate_all() -> None:
    """Parse every registered knob, raising ``EngineError`` on the first
    malformed value — the whole-registry extension of PR 3's
    ``validate_strategy_env``: a bad knob exits 2 up front on every
    engine, never mid-run from inside a trace."""
    for name in REGISTRY:
        get(name)


def unknown_env() -> list[tuple[str, str | None]]:
    """``VCTPU_*`` variables set in the environment but absent from the
    registry, each with its closest registered name (typo detection) or
    None when nothing is close."""
    out: list[tuple[str, str | None]] = []
    for key in sorted(os.environ):
        if not key.startswith("VCTPU_") or key in REGISTRY:
            continue
        close = difflib.get_close_matches(key, REGISTRY, n=1, cutoff=0.6)
        out.append((key, close[0] if close else None))
    return out


def warn_unknown_env() -> list[str]:
    """Log a startup warning for every unknown ``VCTPU_*`` variable —
    today ``VCTPU_FOERST_STRATEGY=wide`` silently configures nothing.
    Returns the warning strings (for tests)."""
    warnings = []
    for key, suggestion in unknown_env():
        msg = f"unknown environment variable {key} is ignored"
        if suggestion:
            msg += f" — did you mean {suggestion}?"
        warnings.append(msg)
        logger.warning("%s", msg)
    return warnings


HEADER_KEY = "vctpu_knobs"


def header_line() -> str:
    """``##vctpu_knobs=`` listing the explicitly-set scoring knobs
    (``in_header=True``) — provenance next to ``##vctpu_engine=`` /
    ``##vctpu_forest_strategy=``, which record the engine-selection knobs
    in resolved form. Execution-only knobs (threads, timeouts, caches)
    are excluded: they are byte-neutral by contract, and the streaming /
    serial / resumed paths must emit identical header bytes under
    differing values of them."""
    parts = [f"{name}={get(name)}"
             for name in sorted(REGISTRY)
             if REGISTRY[name].in_header and raw(name) is not None]
    return f"##{HEADER_KEY}=" + ",".join(parts)


# --------------------------------------------------------------------------
# ``vctpu knobs`` — dump the resolved registry
# --------------------------------------------------------------------------


def run(argv: list[str]) -> int:
    """CLI: print every knob's resolved value and source.

    ``--json`` emits a machine-readable dump. Exit 2 on a malformed
    value (same as every other tool), after reporting WHICH knob."""
    import argparse
    import sys

    parser = argparse.ArgumentParser(
        prog="vctpu knobs",
        description="dump the resolved VCTPU_* knob registry")
    parser.add_argument("--json", action="store_true",
                        help="emit JSON instead of aligned text")
    args = parser.parse_args(argv)
    from variantcalling_tpu.engine import EngineError

    for msg in warn_unknown_env():
        print(f"warning: {msg}")
    try:
        rows = resolved()
    except EngineError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if args.json:
        # the ONE CLI JSON-emission helper (shared with `vctpu obs
        # summary --json`): same indent, ordering and newline contract
        from variantcalling_tpu.utils.jsonio import emit_json

        emit_json({name: {"value": value, "source": src,
                          "help": REGISTRY[name].help}
                   for name, value, src in rows})
        return 0
    width = max(len(name) for name, _, _ in rows)
    for name, value, src in rows:
        shown = "" if value is None else value
        print(f"{name:<{width}}  {shown!s:<12} [{src:>7}]  {REGISTRY[name].help}")
    return 0
