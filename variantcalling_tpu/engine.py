"""Deterministic scoring-engine contract (``VCTPU_ENGINE``).

The filter pipeline can score a FlatForest through two engines that are
required to produce byte-identical formatted output (locked by
``tests/unit/test_engine_contract.py``):

- ``native`` — the C++ host engine (window gather + featurize + forest
  walk in ``native/src``), the CPU fallback hot path;
- ``jit``    — the jitted XLA program (fused featurize + gather-walk /
  GEMM / pallas forest), the accelerator path.

Round-5 VERDICT found the worst failure mode a filtering framework can
have: the engine was chosen PER CALL (``_native_cpu_featurize_score``
returned ``None`` on any hiccup — e.g. g++ build contention under suite
load — and the caller silently fell back to jit), so which engine scored
a run depended on machine load. This module makes the choice a RUN-LEVEL
contract instead:

- the engine is resolved **once per process** (:func:`resolve`), from
  ``VCTPU_ENGINE`` ∈ {``auto``, ``native``, ``jit``} (default ``auto``);
- ``VCTPU_REQUIRE_NATIVE=1`` (or ``VCTPU_ENGINE=native``) **fails loudly**
  (:class:`EngineError`, CLI exit code 2) when the native engine cannot
  build/load — no silent degradation;
- once resolved, **mid-run switching is impossible**: a native hiccup
  after resolution raises instead of degrading to jit
  (``pipelines/filter_variants.py``), and the jit engine never touches the
  native scorer;
- the decision is recorded in the log and in the output VCF header
  (``##vctpu_engine=<name>``) so every output file names the engine that
  produced it.

Scope: the contract covers the **scoring** hot path (featurize + forest
inference). IO-layer native acceleration (BGZF, VCF scan/assemble) keeps
its per-call fallbacks — those paths are byte-identical to their Python
twins by construction and test, so they cannot change output bytes.

Legacy knob: ``VCTPU_NATIVE_FOREST=0`` still forces jit (it predates this
module; ``VCTPU_ENGINE=jit`` is the documented spelling).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, replace

from variantcalling_tpu import knobs, logger
from variantcalling_tpu.utils import degrade

ENGINE_ENV = "VCTPU_ENGINE"
REQUIRE_ENV = "VCTPU_REQUIRE_NATIVE"
HEADER_KEY = "vctpu_engine"


class EngineError(RuntimeError):
    """A requested/resolved engine cannot serve this run. Never caught by
    a fallback — the run fails with a clear message (exit code 2)."""


@dataclass(frozen=True)
class EngineDecision:
    """The resolved, immutable engine choice for this process."""

    name: str  # "native" | "jit"
    requested: str  # "auto" | "native" | "jit" (what the env asked for)
    reason: str  # human-readable resolution rationale

    def header_line(self) -> str:
        return f"##{HEADER_KEY}={self.name}"


_LOCK = threading.Lock()
_RESOLVED: EngineDecision | None = None


def _requested() -> str:
    req = knobs.get_str(ENGINE_ENV)
    if knobs.get_bool(REQUIRE_ENV):
        if req == "jit":
            raise EngineError(
                f"{REQUIRE_ENV}=1 conflicts with {ENGINE_ENV}=jit — drop one")
        req = "native"
    return req


def _native_usable() -> bool:
    from variantcalling_tpu import native

    return native.available()


def _auto_wants_native() -> bool:
    """The auto policy (unchanged from the pre-contract
    ``use_native_cpu_forest``): single local CPU device — the sharded mesh
    path and accelerators stay on XLA."""
    if not knobs.get_bool("VCTPU_NATIVE_FOREST"):
        return False
    try:
        import jax

        return jax.default_backend() == "cpu" and len(jax.local_devices()) == 1
    except Exception as e:  # noqa: BLE001 — backend probe failure: stay on jit
        degrade.record("engine.backend_probe", e, fallback="auto resolves to jit")
        return False


def resolve() -> EngineDecision:
    """Resolve (once per process) and return the engine decision.

    Subsequent calls return the cached decision — the probe that decides
    (native library build/load, backend) runs exactly once, so a later
    build failure or env mutation cannot flip the engine mid-run.
    """
    global _RESOLVED
    with _LOCK:
        if _RESOLVED is not None:
            return _RESOLVED
        req = _requested()
        if req == "native":
            if not _native_usable():
                raise EngineError(
                    "the native scoring engine was required "
                    f"({ENGINE_ENV}=native or {REQUIRE_ENV}=1) but the native "
                    "library failed to build/load on this host (g++ missing, "
                    "build failure, or VCTPU_NO_NATIVE set). Refusing to fall "
                    "back to the jit engine; unset the requirement or fix the "
                    "toolchain. See docs/robustness.md."
                )
            decision = EngineDecision("native", req, "explicitly requested")
        elif req == "jit":
            decision = EngineDecision("jit", req, "explicitly requested")
        elif _auto_wants_native() and _native_usable():
            decision = EngineDecision(
                "native", req, "auto: single local CPU device, native library loaded")
        else:
            decision = EngineDecision("jit", req, "auto: accelerator/mesh backend, "
                                      "VCTPU_NATIVE_FOREST=0, or no native library")
        logger.info("scoring engine resolved: %s (%s)", decision.name, decision.reason)
        # NOTE: no obs event here — resolution is cached per process, so a
        # cache-miss emission would vanish from every later run's stream.
        # The per-run "resolve"/"engine" event is emitted by FilterContext,
        # which pins the decision into each run.
        _RESOLVED = decision
        return decision


def resolve_request() -> EngineDecision:
    """Per-REQUEST engine decision for the ``vctpu serve`` daemon
    (docs/serving.md): an EXPLICIT scoped/env request (``VCTPU_ENGINE``
    under ``knobs.scope``, or ``VCTPU_REQUIRE_NATIVE``) resolves fresh —
    the process cache must not pin request A's engine onto request B —
    while ``auto`` returns the cached process decision (the probe that
    decides auto ran once and its inputs are process facts, not request
    settings). Explicit native still fails loudly when unusable; the
    failure is then a per-request configuration error."""
    req = _requested()
    if req == "auto":
        return resolve()
    if req == "native":
        if not _native_usable():
            raise EngineError(
                "this request requires the native scoring engine "
                f"({ENGINE_ENV}=native or {REQUIRE_ENV}=1) but the native "
                "library is not loaded on this host. See "
                "docs/robustness.md.")
        return EngineDecision("native", req, "explicitly requested (scoped)")
    return EngineDecision("jit", req, "explicitly requested (scoped)")


def resolve_for_run() -> EngineDecision:
    """:func:`resolve` plus multi-host agreement: every rank must score
    with the SAME engine, or the allgathered score slices could mix
    engines within one output file.

    Collective-safe under per-rank failure: a rank whose local resolution
    raised still ENTERS the agreement allgather (with an error token), so
    healthy ranks never deadlock waiting for it — every rank then fails
    the job loudly. Disagreement among healthy ranks downgrades
    auto-resolved ranks to jit; a rank that EXPLICITLY requested native
    raises instead (the fail-loudly contract beats the agreement).
    Call on every rank or none.
    """
    local_error: EngineError | None = None
    decision: EngineDecision | None = None
    try:
        decision = resolve()
    except EngineError as e:
        local_error = e
    try:
        import jax

        n_proc = jax.process_count()
    except Exception as e:  # noqa: BLE001 — uninitialized backend == single process
        degrade.record("engine.process_count_probe", e, fallback="n_proc=1")
        n_proc = 1
    if n_proc <= 1:
        if local_error is not None:
            raise local_error
        return decision
    from variantcalling_tpu.parallel import distributed as dist

    # token carries (resolved name, what was requested) so EVERY rank can
    # compute the SAME verdict from the same gathered list — one rank
    # raising while another proceeds would just move the deadlock to the
    # next collective
    token = "error/-" if local_error is not None \
        else f"{decision.name}/{decision.requested}"
    tokens = [t.split("/", 1) for t in dist.allgather_strings([token])]
    if local_error is not None:
        raise local_error
    names = {t[0] for t in tokens}
    if "error" in names:
        raise EngineError(
            "scoring-engine resolution failed on another rank (see its log "
            "for the cause); failing this rank too so the job exits "
            "consistently instead of deadlocking in a later collective")
    if len(names) > 1:
        if any(req == "native" for _, req in tokens):
            raise EngineError(
                "ranks resolved different scoring engines "
                f"({','.join(sorted(names))}) and at least one rank "
                f"explicitly requires native ({ENGINE_ENV}=native or "
                f"{REQUIRE_ENV}=1) — refusing to downgrade it silently. "
                "Pin the same engine on every rank.")
        downgraded = replace(
            decision, name="jit",
            reason=f"ranks disagreed ({','.join(sorted(names))}): "
                   "pinning every rank to jit")
        logger.warning("scoring engine: %s", downgraded.reason)
        from variantcalling_tpu import obs

        if obs.active():
            obs.event("resolve", "engine", value=downgraded.name,
                      requested=downgraded.requested, reason=downgraded.reason)
        global _RESOLVED
        with _LOCK:
            _RESOLVED = downgraded  # the whole process follows the agreement
        return downgraded
    return decision


def reset_for_tests() -> None:
    """Drop the cached decision so a test can re-resolve under a patched
    env. Production code must never call this — the cache IS the no-switch
    guarantee."""
    global _RESOLVED
    with _LOCK:
        _RESOLVED = None
