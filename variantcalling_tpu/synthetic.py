"""Shared synthetic workloads: the fused filter hot path + input builders.

Single source of truth for bench.py and __graft_entry__.py so the benchmark
and the driver's compile check always measure the same program as the real
pipeline's device stage (featurization kernels + flat-forest inference).
"""

from __future__ import annotations

import numpy as np

from variantcalling_tpu.models.forest import FlatForest, make_predictor
from variantcalling_tpu.ops import features as fops

N_HOT_FEATURES = 12  # features assembled by fused_hot_path below
WINDOW = 41


def synthetic_batch(n: int, rng: np.random.Generator):
    """(windows, scalar dict, is_indel, indel_nuc) shaped like real featurized input."""
    windows = rng.integers(0, 4, size=(n, WINDOW), dtype=np.uint8)
    scalars = {
        "qual": rng.uniform(0, 100, n).astype(np.float32),
        "dp": rng.integers(1, 60, n).astype(np.float32),
        "sor": rng.uniform(0, 4, n).astype(np.float32),
        "af": rng.uniform(0, 1, n).astype(np.float32),
        "gq": rng.integers(0, 99, n).astype(np.float32),
        "is_het": rng.integers(0, 2, n).astype(np.float32),
    }
    is_indel = rng.random(n) < 0.1
    indel_nuc = np.where(is_indel, rng.integers(0, 4, n), 4).astype(np.int32)
    return windows, scalars, is_indel, indel_nuc


def synthetic_forest(rng: np.random.Generator, n_trees: int = 40, depth: int = 12,
                     n_features: int = N_HOT_FEATURES) -> FlatForest:
    """Random but structurally-valid forest: complete binary trees, leaf level at the bottom."""
    m = 2**depth
    feature = rng.integers(0, n_features, size=(n_trees, m)).astype(np.int32)
    left = np.minimum(2 * np.arange(m) + 1, m - 1).astype(np.int32)
    right = np.minimum(2 * np.arange(m) + 2, m - 1).astype(np.int32)
    is_leaf = np.arange(m) >= (m // 2 - 1)
    feature[:, is_leaf] = -1
    return FlatForest(
        feature=feature,
        threshold=rng.uniform(0, 50, size=(n_trees, m)).astype(np.float32),
        left=np.broadcast_to(np.where(is_leaf, np.arange(m), left), (n_trees, m)).astype(np.int32),
        right=np.broadcast_to(np.where(is_leaf, np.arange(m), right), (n_trees, m)).astype(np.int32),
        value=rng.uniform(0, 1, size=(n_trees, m)).astype(np.float32),
        max_depth=depth,
    )


def synthetic_dan(rng: np.random.Generator, feature_names: list[str],
                  embed_dim: int = 4, hidden: int = 16, n_layers: int = 2):
    """Random but structurally-valid DAN over a real feature layout: the
    numeric block is every feature except the motif-code columns, so the
    model scores through the same fused (N, F) matrix path as a trained
    one (models/dan.make_score_predictor). Deterministic in ``rng``."""
    import jax

    from variantcalling_tpu.models import dan as dan_mod

    numeric_features = [f for f in feature_names
                        if f not in ("left_motif", "right_motif")]
    cfg = dan_mod.DanConfig(n_numeric=len(numeric_features),
                            embed_dim=embed_dim, hidden=hidden,
                            n_layers=n_layers)
    params = dan_mod.init_params(
        cfg, jax.random.PRNGKey(int(rng.integers(0, 2**31 - 1))))
    # init_params zeroes the output head (a training-friendly init): a
    # synthetic scorer needs VARYING scores or every parity/digest check
    # downstream would pass trivially on a constant-0.5 output
    params["w_out"] = jax.random.normal(
        jax.random.PRNGKey(int(rng.integers(0, 2**31 - 1))),
        params["w_out"].shape) * (1.0 / np.sqrt(hidden))
    model = dan_mod.DanModel.from_params(
        cfg, params, feature_names=list(feature_names),
        numeric_features=numeric_features)
    # normalization keeps the random logits in sigmoid's useful range for
    # arbitrary feature scales (qual ~ [0, 100], flags ~ {0, 1})
    model.norm_mu = np.zeros(len(numeric_features), np.float32)
    model.norm_sd = np.full(len(numeric_features), 10.0, np.float32)
    return model


def fused_hot_path(forest: FlatForest):
    """The filter device program: windows+scalars -> features -> TREE_SCORE.

    Returns a jittable fn(windows, qual, dp, sor, af, gq, is_het, is_indel,
    indel_nuc) mirroring the pipeline's featurize+score stage. Inference
    strategy picks GEMM (MXU matmuls) on TPU, gather walk on CPU
    (models/forest.make_predictor).
    """
    import jax.numpy as jnp

    predictor = make_predictor(forest, N_HOT_FEATURES)

    def fwd(windows, qual, dp, sor, af, gq, is_het, is_indel, indel_nuc):
        center = windows.shape[1] // 2
        gc = fops.gc_content(windows, center, radius=10)
        hmer_len, hmer_nuc = fops.hmer_indel_features(windows, center, is_indel, indel_nuc)
        left_m, right_m = fops.motif_codes(windows, center)
        x = jnp.stack(
            [
                qual,
                dp,
                sor,
                af,
                gq,
                is_het,
                is_indel.astype(jnp.float32),
                hmer_len.astype(jnp.float32),
                hmer_nuc.astype(jnp.float32),
                gc,
                (left_m % 125).astype(jnp.float32),
                (right_m % 125).astype(jnp.float32),
            ],
            axis=1,
        )
        return predictor(x)

    return fwd


def hot_path_args(n: int, seed: int = 1):
    """Device-ready positional args for fused_hot_path."""
    import jax.numpy as jnp

    rng = np.random.default_rng(seed)
    windows, scalars, is_indel, indel_nuc = synthetic_batch(n, rng)
    return (
        jnp.asarray(windows),
        jnp.asarray(scalars["qual"]),
        jnp.asarray(scalars["dp"]),
        jnp.asarray(scalars["sor"]),
        jnp.asarray(scalars["af"]),
        jnp.asarray(scalars["gq"]),
        jnp.asarray(scalars["is_het"]),
        jnp.asarray(is_indel),
        jnp.asarray(indel_nuc),
    )


def native_hot_path(forest: FlatForest):
    """CPU twin of :func:`fused_hot_path`: the SAME 12 features and forest
    walk, computed by the native engine over host numpy arrays — the stage
    the filter pipeline actually runs on a single-core CPU fallback
    (pipelines/filter_variants._native_cpu_featurize_score). Returns a
    host fn with fused_hot_path's signature, or None when the native
    library is unavailable."""
    from variantcalling_tpu import native
    from variantcalling_tpu.models.forest import native_host_predictor

    nf = native_host_predictor(forest)
    if nf is None or not native.available():
        return None
    fo = np.asarray([3, 2, 1, 0], dtype=np.int32)  # TGCA

    def fwd(windows, qual, dp, sor, af, gq, is_het, is_indel, indel_nuc):
        n = len(qual)
        zeros = np.zeros(n, np.int32)
        no_snp = np.zeros(n, np.uint8)  # cycle-skip unused by this feature set
        dev = native.featurize_windows(windows, windows.shape[1] // 2,
                                       is_indel, indel_nuc, zeros, zeros, no_snp, fo)
        if dev is None:
            return None
        x = np.stack([
            qual, dp, sor, af, gq, is_het,
            np.asarray(is_indel, np.float32),
            dev["hmer_indel_length"].astype(np.float32),
            dev["hmer_indel_nuc"].astype(np.float32),
            dev["gc_content"],
            (dev["left_motif"] % 125).astype(np.float32),
            (dev["right_motif"] % 125).astype(np.float32),
        ], axis=1)
        return nf(x)

    return fwd


def host_hot_path_args(n: int, seed: int = 1):
    """Host numpy positional args for native_hot_path (same distribution
    as hot_path_args)."""
    rng = np.random.default_rng(seed)
    windows, scalars, is_indel, indel_nuc = synthetic_batch(n, rng)
    return (windows, scalars["qual"], scalars["dp"], scalars["sor"],
            scalars["af"], scalars["gq"], scalars["is_het"], is_indel, indel_nuc)
