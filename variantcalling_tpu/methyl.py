"""Methylation postprocessing core (MethylDackel output → metrics tensors).

Reference surface: the five ugbio_methylation CLI tools registered at
ugvc/__main__.py:20-26,58-64 (concat_methyldackel_csvs, process_mbias,
process_merge_context[_no_cp_g], process_per_read); their internals live in
the missing ugbio_utils submodule, so behavior is re-derived from
MethylDackel's public output formats:

- ``extract`` bedGraph rows: chrom, start, end, meth_pct, n_meth, n_unmeth
- ``mbias --txt`` rows: strand (OT/OB/CTOT/CTOB), read (1/2), position,
  n_meth, n_unmeth
- ``perRead`` rows: read, chrom, pos, meth_fraction, n_sites

Aggregations (methylation histograms, coverage×methylation joint stats,
per-position M-bias curves) are batched device reductions — one-hot psum
style, the same kernel family as ops/coverage histograms.
"""

from __future__ import annotations

import numpy as np
import pandas as pd

import jax.numpy as jnp

BEDGRAPH_COLS = ["chrom", "start", "end", "meth_pct", "n_meth", "n_unmeth"]
MBIAS_COLS = ["strand", "read", "position", "n_meth", "n_unmeth"]


def read_extract_bedgraph(path: str) -> pd.DataFrame:
    """MethylDackel extract output (with or without the track header line)."""
    df = pd.read_csv(path, sep="\t", comment="t", header=None, names=BEDGRAPH_COLS)
    # "comment='t'" drops the 'track ...' header; re-validate dtypes
    df = df[pd.to_numeric(df["start"], errors="coerce").notna()]
    for c in BEDGRAPH_COLS[1:]:
        df[c] = pd.to_numeric(df[c])
    return df.reset_index(drop=True)


def read_mbias_txt(path: str) -> pd.DataFrame:
    df = pd.read_csv(path, sep="\t")
    df.columns = [c.strip().lower().replace("#", "").replace(" ", "_") for c in df.columns]
    rename = {"nmethylated": "n_meth", "nunmethylated": "n_unmeth", "pos": "position"}
    df = df.rename(columns=rename)
    for col in ("strand", "read", "position", "n_meth", "n_unmeth"):
        if col not in df.columns:
            raise ValueError(f"{path}: mbias table missing column {col!r}")
    return df


def methylation_histogram(n_meth: np.ndarray, n_unmeth: np.ndarray, n_bins: int = 101) -> np.ndarray:
    """Histogram of per-site methylation fraction (0..1 in n_bins bins), device-reduced."""
    nm = jnp.asarray(n_meth, dtype=jnp.float32)
    nu = jnp.asarray(n_unmeth, dtype=jnp.float32)
    cov = nm + nu
    frac = jnp.where(cov > 0, nm / jnp.maximum(cov, 1.0), 0.0)
    bins = jnp.clip((frac * (n_bins - 1) + 0.5).astype(jnp.int32), 0, n_bins - 1)
    hist = jnp.zeros(n_bins, dtype=jnp.int32).at[bins].add(jnp.where(cov > 0, 1, 0))
    return np.asarray(hist)


def coverage_methylation_stats(n_meth: np.ndarray, n_unmeth: np.ndarray, max_cov: int = 100) -> pd.DataFrame:
    """Per-coverage-level mean methylation + site counts (joint reduction)."""
    nm = jnp.asarray(n_meth, dtype=jnp.float32)
    nu = jnp.asarray(n_unmeth, dtype=jnp.float32)
    cov = jnp.clip((nm + nu).astype(jnp.int32), 0, max_cov)
    frac = jnp.where(nm + nu > 0, nm / jnp.maximum(nm + nu, 1.0), 0.0)
    counts = jnp.zeros(max_cov + 1, dtype=jnp.int32).at[cov].add(1)
    sums = jnp.zeros(max_cov + 1, dtype=jnp.float32).at[cov].add(frac)
    counts_np = np.asarray(counts)
    mean = np.divide(np.asarray(sums), np.maximum(counts_np, 1), where=counts_np > 0)
    return pd.DataFrame(
        {"coverage": np.arange(max_cov + 1), "n_sites": counts_np, "mean_methylation": np.round(mean, 5)}
    )


def global_methylation_summary(df: pd.DataFrame) -> pd.DataFrame:
    nm = float(df["n_meth"].sum())
    nu = float(df["n_unmeth"].sum())
    cov = df["n_meth"].to_numpy() + df["n_unmeth"].to_numpy()
    return pd.DataFrame(
        [
            {
                "n_sites": len(df),
                "n_covered_sites": int((cov > 0).sum()),
                "total_calls": nm + nu,
                "global_methylation": round(nm / max(nm + nu, 1.0), 5),
                "mean_coverage": round(float(cov.mean()) if len(cov) else 0.0, 3),
            }
        ]
    )


def mbias_curves(df: pd.DataFrame) -> pd.DataFrame:
    """Per (strand, read, position) methylation fraction — the M-bias curve."""
    g = df.groupby(["strand", "read", "position"], as_index=False)[["n_meth", "n_unmeth"]].sum()
    tot = g["n_meth"] + g["n_unmeth"]
    g["methylation"] = np.round(np.where(tot > 0, g["n_meth"] / tot.clip(lower=1), np.nan), 5)
    return g


def mbias_inclusion_bounds(curves: pd.DataFrame, tolerance: float = 0.05) -> pd.DataFrame:
    """Suggested 5'/3' trim bounds per (strand, read): positions whose
    methylation deviates > tolerance from the plateau median are excluded
    (the standard MethylDackel --OT/--OB trimming recommendation)."""
    rows = []
    for (strand, read), grp in curves.groupby(["strand", "read"]):
        grp = grp.sort_values("position")
        m = grp["methylation"].to_numpy()
        pos = grp["position"].to_numpy()
        if len(m) == 0 or np.all(np.isnan(m)):
            continue
        med = np.nanmedian(m)
        ok = np.abs(m - med) <= tolerance
        first = pos[np.argmax(ok)] if ok.any() else pos[0]
        last = pos[len(ok) - 1 - np.argmax(ok[::-1])] if ok.any() else pos[-1]
        rows.append({"strand": strand, "read": read, "inclusion_start": int(first), "inclusion_end": int(last)})
    return pd.DataFrame(rows)


def merge_cpg_strands(df: pd.DataFrame) -> pd.DataFrame:
    """Combine +/- strand CpG records into per-CpG-dinucleotide rows.

    MethylDackel emits one row per cytosine; the C on the reverse strand of
    a CpG sits at start+1. Rows whose start differs by 1 on the same chrom
    are merged by summing counts (the ``--mergeContext`` semantics)."""
    df = df.sort_values(["chrom", "start"]).reset_index(drop=True)
    chrom = df["chrom"].to_numpy()
    start = df["start"].to_numpy()
    prev_same = np.zeros(len(df), dtype=bool)
    if len(df) > 1:
        prev_same[1:] = (chrom[1:] == chrom[:-1]) & (start[1:] == start[:-1] + 1)
    # group id increments where a row does NOT merge with its predecessor
    gid = np.cumsum(~prev_same)
    out = df.groupby(gid).agg(
        chrom=("chrom", "first"),
        start=("start", "first"),
        end=("end", "max"),
        n_meth=("n_meth", "sum"),
        n_unmeth=("n_unmeth", "sum"),
    )
    tot = out["n_meth"] + out["n_unmeth"]
    out["meth_pct"] = np.round(100.0 * out["n_meth"] / tot.clip(lower=1), 2)
    return out.reset_index(drop=True)[BEDGRAPH_COLS[:3] + ["meth_pct", "n_meth", "n_unmeth"]]
