"""Named fault-injection points for robustness testing.

The streaming filter executor, the scoring-engine contract and the
distributed layer all make failure-semantics promises (docs/robustness.md):
a hung stage trips a watchdog, a transient chunk-read error is retried, a
missing native engine under ``VCTPU_REQUIRE_NATIVE=1`` fails loudly instead
of silently degrading, an interrupted run never leaves a partial output at
the destination. Promises like these rot unless the failures themselves are
reproducible — so the failure sites call :func:`check` (or
:func:`should_fire`) on a NAMED injection point, and tests (or an operator,
via the ``VCTPU_FAULTS`` env var) arm exactly the failure they want.

Design rules:

- **Zero cost when disarmed.** ``check()`` is a single module-flag test
  when nothing is armed; production hot paths pay one attribute load.
- **Injected faults look like real faults.** A chunk-read fault raises
  ``OSError(EIO)``, a writeback fault ``OSError(ENOSPC)`` — the handling
  code cannot tell them from the real thing, so the test proves the real
  recovery path.
- **Hangs are cancellable.** An injected hang waits on an event, not a
  bare ``sleep``, so a watchdog that aborts the pipeline can release the
  hung thread (:func:`cancel_hangs`) and still join every worker — the
  "no deadlock, all threads joined" contract stays testable.
- **Deterministic arming.** A fault fires a fixed number of times
  (``times``), then disarms itself; "fail twice then succeed" retry tests
  need no sleeps or probability.

Env syntax (comma-separated)::

    VCTPU_FAULTS="io.chunk_read:2,pipeline.stage_hang@30,io.writeback:0+3"

``point[:times][@seconds][+after]`` — ``times`` defaults to 1 for raising
faults and unlimited for ``native.build`` (an unavailable engine stays
unavailable; 0 or negative also means unlimited); ``@seconds`` turns the
point into a delay/hang of that length (cancellable); ``+after`` grants
that many free passes before the first firing, so subprocess harnesses
(tools/chaoshunt) can schedule mid-stream failures without touching test
APIs.
"""

from __future__ import annotations

import contextvars
import errno
import threading

#: Catalog of injection points: name -> (description, exception factory).
#: ``None`` factory means the point is availability-style: sites ask
#: :func:`should_fire` and handle the failure themselves (no raise).
POINTS: dict[str, tuple[str, object]] = {
    "native.build": (
        "native engine build/load failure (native.get_lib returns None)",
        None,
    ),
    "io.chunk_read": (
        "transient IO error reading/parsing one streaming ingest chunk",
        lambda: OSError(errno.EIO, "injected fault: chunk read error"),
    ),
    "pipeline.stage": (
        "exception inside a streaming pipeline stage body",
        lambda: RuntimeError("injected fault: stage exception"),
    ),
    "pipeline.stage_hang": (
        "hung/slow streaming pipeline stage (cancellable wait)",
        None,  # delay-style: arm with seconds
    ),
    "pipeline.chunk": (
        "per-chunk scoring failure inside the supervised recovery guard "
        "(retried, then quarantined when VCTPU_QUARANTINE=1)",
        lambda: RuntimeError("injected fault: chunk scoring failure"),
    ),
    "xla.dispatch_oom": (
        "XLA device dispatch failure on a mesh megabatch "
        "(RESOURCE_EXHAUSTED — triggers the megabatch-shrink/dp-degrade "
        "rungs of the recovery ladder)",
        lambda: RuntimeError(
            "RESOURCE_EXHAUSTED: injected fault: device OOM during "
            "scoring dispatch"),
    ),
    "io.commit": (
        "ENOSPC at the atomic output commit (os.replace onto the "
        "destination)",
        lambda: OSError(errno.ENOSPC,
                        "injected fault: no space left on device at commit"),
    ),
    "io.writeback": (
        "writeback IO error (ENOSPC) on the streaming output sink",
        lambda: OSError(errno.ENOSPC, "injected fault: no space left on device"),
    ),
    "io.shard_decompress": (
        "IO worker death mid-BGZF-shard-inflate (parallel ingest)",
        lambda: OSError(errno.EIO, "injected fault: shard inflate error"),
    ),
    "io.shard_compress": (
        "worker death mid-BGZF-block-compress (parallel writeback)",
        lambda: OSError(errno.EIO, "injected fault: shard compress error"),
    ),
    "dist.rank_timeout": (
        "one rank entering a collective late (cancellable delay)",
        None,  # delay-style
    ),
    "cache.entry_read": (
        "IO error reading a chunk-cache entry (degrades to a miss — the "
        "chunk recomputes; torn/poisoned CONTENT needs no injection, the "
        "CRC check catches it)",
        lambda: OSError(errno.EIO, "injected fault: cache entry read error"),
    ),
    "cache.entry_write": (
        "chunk-cache entry publication failure — armed with seconds it "
        "hangs MID-entry-write (the chaoshunt cache_torn SIGKILL window) "
        "before raising; the entry is dropped, output bytes unaffected",
        lambda: OSError(errno.ENOSPC,
                        "injected fault: no space left writing cache entry"),
    ),
}

_LOCK = threading.Lock()
_ARMED: dict[str, "_Fault"] = {}
#: fast-path flag — hot sites check this before taking the lock
_ACTIVE = False

#: open scope layers (below), registered so :func:`cancel_hangs` can
#: release scoped hangs too — the watchdog recovering one request must
#: be able to cancel that request's injected hang
_OPEN_SCOPES: list[dict] = []

#: request-scoped fault layer (``faults.scope`` — the ``vctpu serve``
#: per-request poison channel): a dict of armed faults carried in a
#: contextvar, consulted BEFORE the process-global ``_ARMED`` table so
#: one request's injected failure can never fire inside a concurrent
#: request's body. The executor propagates the submitting context into
#: its worker pools (parallel/pipeline.py), so the scope follows the
#: request's chunks. Firing state is shared across the scope's threads
#: (one dict object), mutated under ``_LOCK`` like the global table.
_SCOPE_ARMED: contextvars.ContextVar[dict[str, "_Fault"] | None] = \
    contextvars.ContextVar("vctpu_fault_scope", default=None)
#: count of OPEN fault scopes — keeps the ``_ACTIVE`` fast path honest
#: (a scoped fault must fire even when the global table is empty)
_N_SCOPES = 0


class _Fault:
    __slots__ = ("point", "times", "seconds", "after", "fired", "cancel")

    def __init__(self, point: str, times: int | None, seconds: float | None,
                 after: int = 0):
        self.point = point
        self.times = times
        self.seconds = seconds
        self.after = after  # free passes before the first firing
        self.fired = 0
        #: PER-FAULT hang release (was one process-global latch): a
        #: newly armed hang always hangs (fresh Event — nothing to
        #: clear), and releasing one run's hangs cannot be undone by a
        #: concurrent request arming its own scope
        self.cancel = threading.Event()

    def _take(self) -> bool:
        """Consume one firing; False once the budget is spent."""
        if self.after > 0:
            self.after -= 1
            return False
        if self.times is not None and self.fired >= self.times:
            return False
        self.fired += 1
        return True


def _refresh_active() -> None:
    global _ACTIVE
    _ACTIVE = bool(_ARMED) or _N_SCOPES > 0


def arm(point: str, times: int | None = 1, seconds: float | None = None,
        after: int = 0) -> None:
    """Arm ``point`` to fire ``times`` times (None = unlimited).

    ``seconds`` turns a raising point into a delay and is the wait length
    for delay-style points (``pipeline.stage_hang``, ``dist.rank_timeout``).
    ``after`` grants that many free passes before the first firing — for
    "succeed N times, then fail" mid-stream scenarios.
    """
    if point not in POINTS:
        raise KeyError(f"unknown fault point {point!r}; see faults.POINTS")
    with _LOCK:
        _ARMED[point] = _Fault(point, times, seconds, after=after)
        _refresh_active()


def disarm(point: str) -> None:
    with _LOCK:
        _ARMED.pop(point, None)
        _refresh_active()


def reset() -> None:
    """Disarm everything (test teardown). Per-fault cancel events die
    with their faults, so there is no latch to clear."""
    with _LOCK:
        _ARMED.clear()
        _refresh_active()


def fired(point: str) -> int:
    """How many times ``point`` has fired (0 when never armed)."""
    with _LOCK:
        f = _ARMED.get(point)
        return f.fired if f is not None else 0


def cancel_hangs() -> None:
    """Release every in-flight injected hang (watchdog/teardown path) —
    process-global faults AND every open scope's (the watchdog serving
    a request must release that request's scoped hang). Per-fault
    events: a hang ARMED after this call waits normally (its Event is
    fresh), so no latch-clearing is needed anywhere."""
    with _LOCK:
        targets = list(_ARMED.values()) + [
            f for layer in _OPEN_SCOPES for f in layer.values()]
    for f in targets:
        f.cancel.set()


def _record_firing(point: str, style: str, seconds: float | None = None) -> None:
    """Every injected-fault firing lands in the obs run stream (when one
    is active) — the unified log shows exactly which failures a test or
    chaos run injected, next to the spans/retries they provoked."""
    from variantcalling_tpu import obs

    if obs.active():
        fields = {"style": style}
        if seconds is not None:
            fields["seconds"] = seconds
        obs.event("fault", point, **fields)
        obs.counter("faults.fired").add(1)


def _armed_fault(point: str) -> "_Fault | None":
    """The fault governing ``point`` in this context: the scope layer
    wins (a per-request poison must not also consume the global table's
    budget), else the process-global table. Callers hold ``_LOCK``."""
    layer = _SCOPE_ARMED.get()
    if layer is not None and point in layer:
        return layer[point]
    return _ARMED.get(point)


def should_fire(point: str) -> bool:
    """Availability-style query: does ``point`` fire now? (no raise/sleep).

    Used by sites that express the fault themselves — e.g. the native
    library loader returns None for a "build failure"."""
    if not _ACTIVE:
        return False
    with _LOCK:
        f = _armed_fault(point)
        fire = f is not None and f._take()
    if fire:
        _record_firing(point, "availability")
    return fire


def check(point: str) -> None:
    """Fire ``point`` if armed: sleep for delay-style points (cancellable),
    raise the catalogued exception otherwise. No-op when disarmed."""
    if not _ACTIVE:
        return
    with _LOCK:
        f = _armed_fault(point)
        if f is None or not f._take():
            return
        seconds = f.seconds
    _desc, exc_factory = POINTS[point]
    _record_firing(point, "delay" if seconds is not None else "raise",
                   seconds=seconds)
    if seconds is not None:
        # cancellable: a watchdog that aborts the run can release us so
        # the owning thread still joins (per-fault event — releasing
        # this hang cannot affect a concurrent scope's faults)
        f.cancel.wait(seconds)
        if exc_factory is None:
            return
    if exc_factory is None:
        return
    raise exc_factory()


def parse_spec(spec: str) -> list[tuple[str, int | None, float | None, int]]:
    """Parse a ``VCTPU_FAULTS``-grammar string into a list of
    ``(point, times, seconds, after)`` tuples (module docstring for the
    grammar). Unknown points are dropped, matching the env path's
    tolerance — subprocess harnesses arm against old/new trees alike."""
    out: list[tuple[str, int | None, float | None, int]] = []
    for item in (spec or "").split(","):
        item = item.strip()
        if not item:
            continue
        after = 0
        if "+" in item:
            item, after_s = item.rsplit("+", 1)
            try:
                after = max(0, int(after_s))
            except ValueError:
                after = 0
        seconds = None
        if "@" in item:
            item, sec_s = item.split("@", 1)
            try:
                seconds = float(sec_s)
            except ValueError:
                seconds = None
        times: int | None = 1
        explicit_times = ":" in item
        if explicit_times:
            item, times_s = item.split(":", 1)
            try:
                times = int(times_s)
            except ValueError:
                times = 1
            if times <= 0:
                times = None  # 0 / negative = unlimited
        if item == "native.build" and not explicit_times:
            times = None  # an unavailable engine stays unavailable
        if item in POINTS:
            out.append((item, times, seconds, after))
    return out


class scope:
    """Context-scoped fault arming (the ``vctpu serve`` per-request
    poison channel): the given ``VCTPU_FAULTS``-grammar spec is armed
    for the current execution context only — :func:`check` inside the
    scope fires these faults; concurrent contexts (other requests) see
    only their own scopes and the process-global table. An empty spec
    is a no-op scope, so callers need not branch."""

    __slots__ = ("spec", "_token", "_layer")

    def __init__(self, spec: str):
        self.spec = spec or ""
        self._token = None
        self._layer: dict | None = None

    def __enter__(self) -> "scope":
        global _N_SCOPES
        parsed = parse_spec(self.spec)
        if not parsed:
            return self
        self._layer = {point: _Fault(point, times, seconds, after=after)
                       for point, times, seconds, after in parsed}
        with _LOCK:
            self._token = _SCOPE_ARMED.set(self._layer)
            _OPEN_SCOPES.append(self._layer)
            _N_SCOPES += 1
            _refresh_active()
        return self

    def __exit__(self, *exc) -> bool:
        global _N_SCOPES
        if self._token is not None:
            with _LOCK:
                _SCOPE_ARMED.reset(self._token)
                self._token = None
                try:
                    _OPEN_SCOPES.remove(self._layer)
                except ValueError:  # pragma: no cover — enter/exit paired
                    pass
                _N_SCOPES -= 1
                _refresh_active()
        return False


def _arm_from_env() -> None:
    """Parse ``VCTPU_FAULTS`` (see module docstring) — once at import, so
    subprocess-based tests can arm faults without touching test APIs."""
    from variantcalling_tpu import knobs

    spec = (knobs.get_str("VCTPU_FAULTS") or "").strip()
    for point, times, seconds, after in parse_spec(spec):
        arm(point, times=times, seconds=seconds, after=after)


_arm_from_env()
