"""Designated degradation recorder — the ONLY sanctioned way to swallow
a broad exception.

Round-5's worst bug was a bare ``except`` that silently flipped the
scoring engine per call; PR 2 made engine degradation loud, and the
``vctpu-lint`` VCT002 checker (docs/static_analysis.md) now flags every
``except:`` / ``except Exception:`` that swallows and continues. Some
swallows are legitimate — a backend probe on an uninitialized jax
runtime, a best-effort cache write — but "legitimate" must still be
**visible**: such a handler routes through :func:`record`, which logs the
event with its fallback and keeps a bounded in-process trail
(:data:`EVENTS`) so tests and operators can assert exactly which
degradations a run took. A broad handler that neither re-raises, raises
``EngineError``, nor calls ``degrade.record`` is a VCT002 finding.

Scoring-path code must NOT use this to degrade the engine or strategy —
those contracts fail loudly (``EngineError``, exit 2); :func:`record` is
for probes and best-effort accelerators whose fallback cannot change
output bytes.
"""

from __future__ import annotations

import threading
from collections import deque

from variantcalling_tpu import logger

#: bounded trail of (point, exception repr, fallback) — newest last
EVENTS: deque[tuple[str, str, str]] = deque(maxlen=256)
_LOCK = threading.Lock()


def record(point: str, exc: BaseException | None = None,
           fallback: str = "", warn: bool = False) -> None:
    """Record one sanctioned degradation.

    ``point`` names the site (dotted, like a fault-injection point, e.g.
    ``"engine.backend_probe"``); ``fallback`` says what the code does
    instead. Routine probes (an uninitialized backend on a single host)
    log at DEBUG; pass ``warn=True`` when a human should notice (a cache
    that stopped persisting, an accelerator that stopped accelerating).
    """
    exc_text = "" if exc is None else f"{type(exc).__name__}: {exc}"
    with _LOCK:
        EVENTS.append((point, exc_text, fallback))
    # every sanctioned degradation also lands in the obs run stream (one
    # ordered log with spans/faults/lifecycle — docs/observability.md)
    from variantcalling_tpu import obs

    if obs.active():
        obs.event("degrade", point, exc=exc_text, fallback=fallback,
                  warn=bool(warn))
        obs.counter("degradations").add(1)
    log = logger.warning if warn else logger.debug
    log("degradation %s: %s -> %s", point, exc_text or "(no exception)",
        fallback or "(continue)")


def events_for(point: str) -> list[tuple[str, str, str]]:
    """The recorded events for one point (tests)."""
    with _LOCK:
        return [e for e in EVENTS if e[0] == point]


def clear_for_tests() -> None:
    with _LOCK:
        EVENTS.clear()
