"""Tracing/profiling: per-stage wall-clock + JAX device profiler, first-class.

The reference's only profiling primitive is an (unused, buggy — it prints
t_start - t_end, a negative duration) wall-clock decorator
(ugvc/utils/decorators.py:4-14) plus simppl's command echo. SURVEY §5.1
makes tracing first-class here:

- ``stage(name)`` / ``@timed``: nested wall-clock spans collected into a
  process-global table every pipeline can dump (``report()``), enabled by
  default (near-zero overhead), logged at DEBUG.
- ``device_trace(logdir)``: context manager around ``jax.profiler`` —
  captures an XLA trace (HLO timelines, fusion views) viewable in
  TensorBoard/Perfetto; no-op if profiling is unavailable.
- ``VCTPU_TRACE=1`` env makes every ``stage`` span print as it closes.
"""

from __future__ import annotations

import contextlib
import functools
import time
from dataclasses import dataclass, field

from variantcalling_tpu import logger
from variantcalling_tpu.utils import degrade
from variantcalling_tpu import knobs


@dataclass
class Span:
    name: str
    seconds: float
    depth: int


@dataclass
class _Tracer:
    spans: list[Span] = field(default_factory=list)
    _depth: int = 0

    def clear(self) -> None:
        self.spans.clear()

    def report(self) -> str:
        lines = ["stage timings:"]
        for s in self.spans:
            lines.append(f"  {'  ' * s.depth}{s.name}: {s.seconds:.3f}s")
        return "\n".join(lines)


TRACER = _Tracer()


@contextlib.contextmanager
def stage(name: str):
    """Nested wall-clock span; spans land in TRACER.spans in close order."""
    TRACER._depth += 1
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dt = time.perf_counter() - t0
        TRACER._depth -= 1
        TRACER.spans.append(Span(name, dt, TRACER._depth))
        if knobs.get_bool("VCTPU_TRACE"):
            logger.info("stage %s: %.3fs", name, dt)
        else:
            logger.debug("stage %s: %.3fs", name, dt)


def timed(fn=None, *, name: str | None = None):
    """Decorator form of ``stage`` (fixes the reference's negative-duration timer)."""

    def deco(f):
        label = name or f.__qualname__

        @functools.wraps(f)
        def wrapper(*args, **kwargs):
            with stage(label):
                return f(*args, **kwargs)

        return wrapper

    return deco(fn) if fn is not None else deco


def report() -> str:
    return TRACER.report()


@contextlib.contextmanager
def device_trace(logdir: str):
    """Capture a JAX/XLA device trace into ``logdir`` (TensorBoard-viewable)."""
    import jax

    try:
        jax.profiler.start_trace(logdir)
        started = True
    except Exception as e:  # profiling unsupported on this backend/build
        degrade.record("trace.device_trace_start", e, fallback="no device trace")
        logger.warning("device trace unavailable: %s", e)
        started = False
    try:
        yield
    finally:
        if started:
            try:
                jax.profiler.stop_trace()
                logger.info("device trace written to %s", logdir)
            except Exception as e:  # noqa: BLE001
                degrade.record("trace.device_trace_stop", e,
                               fallback="trace may be incomplete")
                logger.warning("device trace stop failed: %s", e)
