"""Tracing/profiling: per-stage wall-clock + JAX device profiler, first-class.

The reference's only profiling primitive is an (unused, buggy — it prints
t_start - t_end, a negative duration) wall-clock decorator
(ugvc/utils/decorators.py:4-14) plus simppl's command echo. SURVEY §5.1
makes tracing first-class here:

- ``stage(name)`` / ``@timed``: nested wall-clock spans collected into a
  process-global table every pipeline can dump (``report()``), enabled by
  default (near-zero overhead), logged at DEBUG. Span collection is
  THREAD-AWARE: nesting depth lives in a ``threading.local`` (streaming
  worker threads used to interleave through one shared ``_depth`` and
  corrupt the whole table's indentation) and each span records the thread
  that closed it; ``report()`` renders per-thread groups.
- every closed span also lands in the obs event stream when a run is
  active (:mod:`variantcalling_tpu.obs`) — trace spans, degradations and
  executor lifecycle unify into ONE ordered JSONL log.
- ``device_trace(logdir)``: context manager around ``jax.profiler`` —
  captures an XLA trace (HLO timelines, fusion views) viewable in
  TensorBoard/Perfetto; no-op if profiling is unavailable.
- ``VCTPU_TRACE=1`` env makes every ``stage`` span print as it closes.
"""

from __future__ import annotations

import contextlib
import functools
import threading
import time
from dataclasses import dataclass, field

from variantcalling_tpu import logger, obs
from variantcalling_tpu.utils import degrade
from variantcalling_tpu import knobs


@dataclass
class Span:
    name: str
    seconds: float
    depth: int
    thread: str = "MainThread"


class _ThreadState(threading.local):
    depth = 0


@dataclass
class _Tracer:
    """Process-global span table; append is thread-safe, depth is
    per-thread (a worker's nesting cannot corrupt another's)."""

    spans: list[Span] = field(default_factory=list)
    _local: _ThreadState = field(default_factory=_ThreadState, repr=False)
    _lock: threading.Lock = field(default_factory=threading.Lock, repr=False)

    def clear(self) -> None:
        with self._lock:
            self.spans.clear()

    def report(self) -> str:
        """Per-thread groups: the main thread's spans first (unlabeled,
        the historical format), every worker thread after, labeled."""
        with self._lock:
            spans = list(self.spans)
        threads = ["MainThread"] + sorted(
            {s.thread for s in spans} - {"MainThread"})
        lines = ["stage timings:"]
        for t in threads:
            mine = [s for s in spans if s.thread == t]
            if not mine:
                continue
            if t != "MainThread":
                lines.append(f"  [thread {t}]")
            pad = "  " if t == "MainThread" else "    "
            for s in mine:
                lines.append(f"{pad}{'  ' * s.depth}{s.name}: {s.seconds:.3f}s")
        return "\n".join(lines)


TRACER = _Tracer()


@contextlib.contextmanager
def stage(name: str):
    """Nested wall-clock span; spans land in TRACER.spans in close order
    (per thread), and in the obs stream when a run is active."""
    local = TRACER._local
    local.depth += 1
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dt = time.perf_counter() - t0
        local.depth -= 1
        thread = threading.current_thread().name
        with TRACER._lock:
            TRACER.spans.append(Span(name, dt, local.depth, thread))
        if obs.active():
            obs.span(name, dt, thread, depth=local.depth)
        if knobs.get_bool("VCTPU_TRACE"):
            logger.info("stage %s: %.3fs", name, dt)
        else:
            logger.debug("stage %s: %.3fs", name, dt)


def timed(fn=None, *, name: str | None = None):
    """Decorator form of ``stage`` (fixes the reference's negative-duration timer)."""

    def deco(f):
        label = name or f.__qualname__

        @functools.wraps(f)
        def wrapper(*args, **kwargs):
            with stage(label):
                return f(*args, **kwargs)

        return wrapper

    return deco(fn) if fn is not None else deco


def report() -> str:
    return TRACER.report()


@contextlib.contextmanager
def device_trace(logdir: str):
    """Capture a JAX/XLA device trace into ``logdir`` (TensorBoard-viewable)."""
    import jax

    try:
        jax.profiler.start_trace(logdir)
        started = True
    except Exception as e:  # profiling unsupported on this backend/build
        degrade.record("trace.device_trace_start", e, fallback="no device trace")
        logger.warning("device trace unavailable: %s", e)
        started = False
    if started and obs.active():
        obs.event("stage", "device_trace_start", logdir=logdir)
    try:
        yield
    finally:
        if started:
            try:
                jax.profiler.stop_trace()
                logger.info("device trace written to %s", logdir)
                obs.event("stage", "device_trace_stop", logdir=logdir)
            except Exception as e:  # noqa: BLE001
                degrade.record("trace.device_trace_stop", e,
                               fallback="trace may be incomplete")
                logger.warning("device trace stop failed: %s", e)
