"""Flow-space sequence encoding (Ultima flow cycles).

Parity target: ``ugbio_core.flow_format.flow_based_read.
generate_key_from_sequence`` as exercised by collect_hpol_table.py:99 —
encode a base sequence into per-flow homopolymer counts for a cyclic flow
order (default TGCA). Implemented as vectorized run-length encoding: one
pass builds (base, run-length) pairs, cyclic deltas place each run at its
flow index, and the key is one scatter.
"""

from __future__ import annotations

import numpy as np

DEFAULT_FLOW_ORDER = "TGCA"


def generate_key_from_sequence(sequence: str, flow_order: str = DEFAULT_FLOW_ORDER, non_standard_as_a: bool = False) -> np.ndarray:
    """Flow-space key: key[f] = hmer length consumed at flow f.

    Raises ValueError on non-ACGT bases unless ``non_standard_as_a``.
    """
    cycle = len(flow_order)
    base_to_flow = {b: i for i, b in enumerate(flow_order)}
    seq = sequence.upper()
    codes = np.frombuffer(seq.encode("ascii"), dtype=np.uint8)
    lut = np.full(256, -1, dtype=np.int8)
    for b, i in base_to_flow.items():
        lut[ord(b)] = i
    flow_idx = lut[codes]
    if (flow_idx < 0).any():
        if not non_standard_as_a:
            raise ValueError("Non-standard nucleotide in sequence")
        flow_idx = np.where(flow_idx < 0, base_to_flow["A"], flow_idx)
    if len(flow_idx) == 0:
        return np.zeros(0, dtype=np.int64)

    # run-length encode
    boundaries = np.nonzero(np.diff(flow_idx) != 0)[0] + 1
    starts = np.concatenate([[0], boundaries])
    run_bases = flow_idx[starts].astype(np.int64)
    run_lens = np.diff(np.concatenate([starts, [len(flow_idx)]]))

    # cyclic flow position of each run: advance ((next - cur - 1) mod cycle) + 1
    deltas = np.empty(len(run_bases), dtype=np.int64)
    deltas[0] = run_bases[0]  # flows skipped from cycle start
    if len(run_bases) > 1:
        deltas[1:] = (run_bases[1:] - run_bases[:-1] - 1) % cycle + 1
    flow_pos = np.cumsum(deltas)

    key = np.zeros(int(flow_pos[-1]) + 1, dtype=np.int64)
    key[flow_pos] = run_lens
    return key


def key_to_base_index(key: np.ndarray) -> np.ndarray:
    """Base offset at which each flow starts (cumsum of the key, shifted)."""
    k2base = np.cumsum(key)
    return np.concatenate([[0], k2base[:-1]])
