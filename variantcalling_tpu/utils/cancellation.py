"""Cooperative request cancellation for long-running pipeline bodies.

``vctpu serve`` gives every request a deadline (docs/serving.md); a
request that blows it must stop consuming the daemon's cores — but a
streaming run cannot be killed from outside without tearing its
journal/partial protocol. The contract here is cooperative and chunk-
granular: the serve layer binds a :class:`CancelToken` to the request's
execution context, a deadline reaper (or a disconnect detector) trips
the token from any thread, and the streaming commit loop polls
:func:`check` once per chunk — the run then unwinds through its normal
failure teardown (workers joined, partial+journal kept for resume or
discarded), exactly as if a chunk had failed.

The token rides a ``contextvars.ContextVar`` so concurrent requests can
never trip each other, and the executor's context propagation
(parallel/pipeline.py) carries it onto pooled workers. Checking is one
contextvar read when no scope is bound — cheap enough for per-chunk
cadence, invisible to CLI runs (no scope, no cost).

This module is deliberately free of serve imports so the pipelines can
poll it without a dependency cycle.
"""

from __future__ import annotations

import contextvars
import threading

_TOKEN: contextvars.ContextVar["CancelToken | None"] = \
    contextvars.ContextVar("vctpu_cancel_token", default=None)


class CancelledError(RuntimeError):
    """The bound scope's work was cancelled (deadline expiry, client
    disconnect, daemon drain timeout). Deliberately NOT an
    ``EngineError``: cancellation is a per-request outcome, not a
    configuration error."""


class CancelToken:
    """One cancellable unit of work (a serve request). ``cancel`` may be
    called from any thread, any number of times; the first reason wins."""

    __slots__ = ("_event", "reason")

    def __init__(self):
        self._event = threading.Event()
        self.reason: str = ""

    def cancel(self, reason: str = "cancelled") -> None:
        if not self._event.is_set():
            self.reason = reason
            self._event.set()

    @property
    def cancelled(self) -> bool:
        return self._event.is_set()


class scope:
    """Bind ``token`` to the current execution context (restores the
    previous binding on exit, so nested/sibling scopes stay correct)."""

    __slots__ = ("token", "_cv_token")

    def __init__(self, token: CancelToken):
        self.token = token
        self._cv_token = None

    def __enter__(self) -> CancelToken:
        self._cv_token = _TOKEN.set(self.token)
        return self.token

    def __exit__(self, *exc) -> bool:
        _TOKEN.reset(self._cv_token)
        self._cv_token = None
        return False


def current() -> CancelToken | None:
    """The context's bound token (None outside any scope)."""
    return _TOKEN.get()


def check(what: str = "run") -> None:
    """Raise :class:`CancelledError` when the context's token (if any)
    has been tripped — the ONE polling point pipeline loops call."""
    token = _TOKEN.get()
    if token is not None and token.cancelled:
        raise CancelledError(
            f"{what} cancelled: {token.reason or 'cancelled'}")
