"""Host-side statistics with the reference API surface (no sklearn/scipy deps).

Parity target: ``ugvc/utils/stats_utils.py`` in the reference — same
function names, arguments, and numeric behavior (hand-computed expectations
ported in tests/unit/test_stats_utils.py), independently implemented:
the multinomial family runs in log space (the reference divides raw
scipy.stats pmf values, which underflow at depth), and the FN-mask-aware
precision/recall curve sits on a native cumulative-count curve instead of
sklearn. Batched device versions live in
:mod:`variantcalling_tpu.ops.stats`.
"""

from __future__ import annotations

import math

import numpy as np

from variantcalling_tpu.utils.math_utils import safe_divide

# ---------------------------------------------------------------------------
# Goodness-of-fit (multinomial) machinery — the SEC per-locus test substrate.
# ---------------------------------------------------------------------------


def scale_contingency_table(table: list[int], n: int) -> list[int]:
    """Rescale a count table so its total is ~n (rounded). Parity: stats_utils.py:12-29."""
    total = int(np.sum(table))
    if total <= 0:
        return table
    return np.rint(np.multiply(table, n / total)).astype(int).tolist()


def correct_multinomial_frequencies(counts: list[int]) -> np.ndarray:
    """Add-one-corrected category frequencies. Parity: stats_utils.py:32-45."""
    c = np.asarray(counts, dtype=float) + 1.0
    return c / c.sum()


def multinomial_log_likelihood(actual, expected) -> float:
    """Log-likelihood of ``actual`` under the add-one-corrected multinomial
    fit to ``expected`` — the stable primitive the likelihood/ratio pair
    shares (the device twin is ops.stats.multinomial_log_pmf)."""
    x = np.asarray(actual, dtype=float)
    p = correct_multinomial_frequencies(expected)
    coeff = math.lgamma(x.sum() + 1.0) - sum(math.lgamma(v + 1.0) for v in x)
    with np.errstate(divide="ignore"):
        terms = np.where(x > 0, x * np.log(p), 0.0)
    return coeff + float(terms.sum())


def multinomial_likelihood(actual: list[int], expected: list[int]) -> float:
    """Likelihood of ``actual`` under the add-one-corrected fit to
    ``expected``. Parity: stats_utils.py:48-63."""
    return float(np.exp(multinomial_log_likelihood(actual, expected)))


def multinomial_likelihood_ratio(actual: list[int], expected: list[int]) -> tuple[float, float]:
    """(likelihood, likelihood / max-likelihood-under-self-fit).

    Parity: stats_utils.py:66-70, but the ratio is formed in log space —
    at WGS depths both likelihoods underflow float64 and the reference's
    raw division degrades to 0/0.
    """
    log_l = multinomial_log_likelihood(actual, expected)
    log_max = multinomial_log_likelihood(actual, actual)
    return float(np.exp(log_l)), float(np.exp(log_l - log_max))


# ---------------------------------------------------------------------------
# Precision / recall metrics
# ---------------------------------------------------------------------------


def get_precision(false_positives: int, true_positives: int, return_if_denominator_is_0=1) -> float:
    """Precision from fp/tp counts. Parity: stats_utils.py:76-94."""
    called = false_positives + true_positives
    return true_positives / called if called else return_if_denominator_is_0


def get_recall(false_negatives: int, true_positives: int, return_if_denominator_is_0=1) -> float:
    """Recall from fn/tp counts. Parity: stats_utils.py:97-116."""
    truth = false_negatives + true_positives
    return true_positives / truth if truth else return_if_denominator_is_0


def get_f1(precision: float, recall: float, null_value=np.nan) -> float:
    """Harmonic mean with null propagation. Parity: stats_utils.py:119-138."""
    if {precision, recall} & {null_value}:
        return null_value
    return safe_divide(2 * precision * recall, precision + recall)


def binary_clf_curve(y_true: np.ndarray, y_score: np.ndarray) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(fps, tps, thresholds) at each distinct score, descending-score order.

    Native equivalent of sklearn's ``_binary_clf_curve`` so the framework
    carries no sklearn runtime dependency on the metrics path.
    """
    y_true = np.asarray(y_true).astype(bool)
    y_score = np.asarray(y_score, dtype=float)
    desc = np.argsort(y_score, kind="stable")[::-1]
    y_score = y_score[desc]
    y_true = y_true[desc]
    distinct = np.where(np.diff(y_score))[0]
    threshold_idxs = np.r_[distinct, y_true.size - 1]
    tps = np.cumsum(y_true)[threshold_idxs]
    fps = 1 + threshold_idxs - tps
    return fps, tps, y_score[threshold_idxs]


def _precision_recall_points(y_true: np.ndarray, y_score: np.ndarray) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """sklearn.metrics.precision_recall_curve semantics, natively."""
    fps, tps, thresholds = binary_clf_curve(y_true, y_score)
    ps = tps + fps
    precision = np.zeros_like(tps, dtype=float)
    np.divide(tps, ps, out=precision, where=ps != 0)
    if tps[-1] == 0:
        recall = np.ones_like(tps, dtype=float)
    else:
        recall = tps / tps[-1]
    # reverse so recall is decreasing; append the (1, 0) endpoint
    sl = slice(None, None, -1)
    return (
        np.hstack((precision[sl], 1)),
        np.hstack((recall[sl], 0)),
        thresholds[sl],
    )


def precision_recall_curve(
    gtr: np.ndarray,
    predictions: np.ndarray,
    fn_mask: np.ndarray,
    pos_label: str | int | None = 1,
    min_class_counts_to_output: int = 20,
) -> tuple:
    """FN-mask-aware precision/recall curve. Parity: stats_utils.py:141-210.

    ``fn_mask`` marks variants that were false negatives (present in the
    ground truth but carrying no usable prediction): they contribute no
    curve points, but recall is shrunk by ``tp/(tp+fn)`` so every missed
    call still counts against it. The noisy high-threshold tail — points
    supported by fewer than ``min_class_counts_to_output`` predictions —
    is dropped.
    """
    labels = np.asarray(gtr)
    scores = np.asarray(predictions)
    missed = np.asarray(fn_mask, dtype=bool)
    if labels.size == 0:
        return np.array([]), np.array([]), np.array([]), np.array([])
    assert np.unique(labels.astype("U") if labels.dtype == object else labels).size <= 2, \
        "variant labels must be binary"
    assert missed.size == scores.size, "fn_mask must align with predictions"

    scored = ~missed
    truth = labels[scored] == pos_label
    kept_scores = scores[scored]

    if truth.size:
        prec_pts, rec_pts, thr_pts = _precision_recall_points(truth, kept_scores)
    else:  # everything was missed: a degenerate two-point curve
        prec_pts = np.array([0.0, 1.0])
        rec_pts = np.array([1.0, 0.0])
        thr_pts = np.array([kept_scores.min() if kept_scores.size else 0])

    # interior points only: strip the synthetic (1, 0) endpoint and the
    # lowest-threshold point, then re-base recall onto the full truth set
    n_tp = truth.sum()
    shrink = safe_divide(n_tp, n_tp + int(missed.sum()))
    prec = prec_pts[1:-1]
    rec = rec_pts[1:-1] * shrink
    thr = thr_pts[1:]
    f1 = 2 * prec * rec / (prec + rec + np.finfo(float).eps)

    if kept_scores.size:
        cutoff = np.sort(kept_scores)[max(0, kept_scores.size - min_class_counts_to_output)]
    else:
        cutoff = 0
    keep = thr <= cutoff
    return prec[keep], rec[keep], f1[keep], thr[keep]
