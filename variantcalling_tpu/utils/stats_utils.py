"""Host-side statistics with the reference API surface (no sklearn/scipy deps).

Parity target: ``ugvc/utils/stats_utils.py`` in the reference. The
FN-mask-aware precision/recall curve reproduces the reference's
sklearn-based semantics (``stats_utils.py:141-210``) with a native
implementation; batched device versions live in
:mod:`variantcalling_tpu.ops.stats`.
"""

from __future__ import annotations

import math

import numpy as np

from variantcalling_tpu.utils.math_utils import safe_divide

# ---------------------------------------------------------------------------
# Goodness-of-fit (multinomial) machinery — the SEC per-locus test substrate.
# ---------------------------------------------------------------------------


def scale_contingency_table(table: list[int], n: int) -> list[int]:
    """Scale a count table so the total is ~n (rounded). Parity: stats_utils.py:12-29."""
    sum_table = sum(table)
    if sum_table > 0:
        scaled_table = np.array(table) * (n / sum_table)
        return list(np.round(scaled_table).astype(int))
    return table


def correct_multinomial_frequencies(counts: list[int]) -> np.ndarray:
    """Add-one-corrected category frequencies. Parity: stats_utils.py:32-45."""
    corrected_counts = np.array(counts) + 1
    return corrected_counts / np.sum(corrected_counts)


def _multinomial_log_pmf(x: np.ndarray, p: np.ndarray) -> float:
    n = int(np.sum(x))
    logp = math.lgamma(n + 1) - float(np.sum([math.lgamma(v + 1) for v in x]))
    with np.errstate(divide="ignore"):
        lp = np.where(x > 0, x * np.log(p), 0.0)
    return logp + float(np.sum(lp))


def multinomial_likelihood(actual: list[int], expected: list[int]) -> float:
    """Likelihood of ``actual`` under the add-one-corrected multinomial fit to ``expected``.

    Parity: stats_utils.py:48-63.
    """
    freq_expected = correct_multinomial_frequencies(expected)
    return float(np.exp(_multinomial_log_pmf(np.asarray(actual, dtype=float), freq_expected)))


def multinomial_likelihood_ratio(actual: list[int], expected: list[int]) -> tuple[float, float]:
    """(likelihood, likelihood / max-likelihood-under-self-fit). Parity: stats_utils.py:66-70."""
    likelihood = multinomial_likelihood(actual, expected)
    max_likelihood = multinomial_likelihood(actual, actual)
    likelihood_ratio = likelihood / max_likelihood
    return likelihood, likelihood_ratio


# ---------------------------------------------------------------------------
# Precision / recall metrics
# ---------------------------------------------------------------------------


def get_precision(false_positives: int, true_positives: int, return_if_denominator_is_0=1) -> float:
    """Precision from fp/tp counts. Parity: stats_utils.py:76-94."""
    if false_positives + true_positives == 0:
        return return_if_denominator_is_0
    return 1 - false_positives / (false_positives + true_positives)


def get_recall(false_negatives: int, true_positives: int, return_if_denominator_is_0=1) -> float:
    """Recall from fn/tp counts. Parity: stats_utils.py:97-116."""
    if false_negatives + true_positives == 0:
        return return_if_denominator_is_0
    return 1 - false_negatives / (false_negatives + true_positives)


def get_f1(precision: float, recall: float, null_value=np.nan) -> float:
    """Harmonic mean with null propagation. Parity: stats_utils.py:119-138."""
    if null_value in {precision, recall}:
        return null_value
    return safe_divide(2 * precision * recall, precision + recall)


def binary_clf_curve(y_true: np.ndarray, y_score: np.ndarray) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(fps, tps, thresholds) at each distinct score, descending-score order.

    Native equivalent of sklearn's ``_binary_clf_curve`` so the framework
    carries no sklearn runtime dependency on the metrics path.
    """
    y_true = np.asarray(y_true).astype(bool)
    y_score = np.asarray(y_score, dtype=float)
    desc = np.argsort(y_score, kind="stable")[::-1]
    y_score = y_score[desc]
    y_true = y_true[desc]
    distinct = np.where(np.diff(y_score))[0]
    threshold_idxs = np.r_[distinct, y_true.size - 1]
    tps = np.cumsum(y_true)[threshold_idxs]
    fps = 1 + threshold_idxs - tps
    return fps, tps, y_score[threshold_idxs]


def _precision_recall_points(y_true: np.ndarray, y_score: np.ndarray) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """sklearn.metrics.precision_recall_curve semantics, natively."""
    fps, tps, thresholds = binary_clf_curve(y_true, y_score)
    ps = tps + fps
    precision = np.zeros_like(tps, dtype=float)
    np.divide(tps, ps, out=precision, where=ps != 0)
    if tps[-1] == 0:
        recall = np.ones_like(tps, dtype=float)
    else:
        recall = tps / tps[-1]
    # reverse so recall is decreasing; append the (1, 0) endpoint
    sl = slice(None, None, -1)
    return (
        np.hstack((precision[sl], 1)),
        np.hstack((recall[sl], 0)),
        thresholds[sl],
    )


def precision_recall_curve(
    gtr: np.ndarray,
    predictions: np.ndarray,
    fn_mask: np.ndarray,
    pos_label: str | int | None = 1,
    min_class_counts_to_output: int = 20,
) -> tuple:
    """FN-mask-aware precision/recall curve. Parity: stats_utils.py:141-210.

    ``fn_mask`` marks variants that were false negatives (missed true calls,
    present in ground truth but carrying no usable prediction); recall is
    rescaled by ``tp/(tp+fn)`` so missed calls count against recall without
    contributing curve points.
    """
    gtr = np.asarray(gtr)
    predictions = np.asarray(predictions)
    fn_mask = np.asarray(fn_mask, dtype=bool)

    if len(gtr) == 0:
        return np.array([]), np.array([]), np.array([]), np.array([])

    assert len(set(gtr.tolist())) <= 2, "Only up to two classes of variant labels are possible"
    assert len(fn_mask) == len(predictions), "FN mask should be of the length of predictions"

    gtr_select = gtr[~fn_mask]
    gtr_select = gtr_select == pos_label
    predictions_select = predictions[~fn_mask]
    original_fn_count = fn_mask.sum()

    if len(gtr_select) > 0:
        raw_precision, raw_recall, thresholds = _precision_recall_points(gtr_select, predictions_select)
    else:
        raw_precision = np.array([0.0, 1.0])
        raw_recall = np.array([1.0, 0.0])
        thresholds = np.array([0]) if len(predictions_select) == 0 else np.array([np.min(predictions_select)])

    recall_correction = safe_divide(gtr_select.sum(), gtr_select.sum() + original_fn_count)
    recalls = raw_recall * recall_correction
    # strip the synthetic (1, 0) endpoint and the initial curve point
    recalls = recalls[1:-1]
    precisions = raw_precision[1:-1]
    thresholds = thresholds[1:]
    f1_score = 2 * (recalls * precisions) / (recalls + precisions + np.finfo(float).eps)

    # drop the noisy low-count tail of the curve
    predictions_select = np.sort(predictions_select)
    if len(predictions_select) > 0:
        threshold_cutoff = predictions_select[max(0, len(predictions_select) - min_class_counts_to_output)]
    else:
        threshold_cutoff = 0

    mask = thresholds > threshold_cutoff
    return precisions[~mask], recalls[~mask], f1_score[~mask], thresholds[~mask]
