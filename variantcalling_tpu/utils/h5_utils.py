"""DataFrame <-> HDF5 store built on h5py.

The reference persists every tabular artifact as pandas ``.to_hdf`` keys
(evaluate_concordance.py:101-105, coverage stats, report sections) via
pytables. This framework keeps the same *surface* — ``write_hdf(df, path,
key)`` / ``read_hdf(path, key, skip_keys)`` with multi-key files and the
``key="all"`` concat convention — on an h5py-backed columnar layout:
one group per key, one dataset per column, dtype metadata in attrs.
Columnar layout means a reader can pull a single column of a multi-GB
store without materializing the frame (the ingest path for device batches).
"""

from __future__ import annotations

import json

import h5py
import numpy as np
import pandas as pd

_FORMAT_ATTR = "vctpu_frame"
# h5py VLEN strings reject NUL bytes; \x01 framing keeps the sentinel
# unambiguous against real data
_NULL = "\x01null\x01"


def _encode_column(vals: np.ndarray):
    """(data, kind) where kind notes how to restore the dtype."""
    if vals.dtype == object and len(vals) and isinstance(vals[0], (np.ndarray, list)):
        # ragged array-valued column (e.g. per-group PR curves) -> CSR layout
        arrays = [np.asarray(v, dtype=np.float64).ravel() for v in vals]
        offsets = np.zeros(len(arrays) + 1, dtype=np.int64)
        np.cumsum([len(a) for a in arrays], out=offsets[1:])
        flat = np.concatenate(arrays) if arrays else np.array([], dtype=np.float64)
        return (flat, offsets), "ragged"
    if vals.dtype == object or vals.dtype.kind in ("U", "S"):
        ser = pd.Series(vals).where(pd.Series(vals).notna(), _NULL).astype(str)
        lens = ser.str.len()
        n = max(len(ser), 1)
        max_len = int(lens.max()) if len(lens) else 1
        total = int(lens.sum())
        # choose the layout from LENGTHS before materializing anything wide:
        # fixed-width bytes write as one block (h5py VLEN strings loop per
        # element), but one outlier string must not blow up a (n, max_len)
        # allocation — VLEN handles that case
        if max_len <= 64 or max_len * n <= 4 * (total + n):
            if ser.str.contains("\x00", regex=False).any():
                # numpy 'S' silently strips trailing NULs; fail loudly like
                # the VLEN path always did
                raise ValueError("NUL bytes in string column are not storable")
            u = np.asarray(ser, dtype="U")
            return np.char.encode(u, "utf-8"), "fstr"
        return ser.to_numpy(dtype=object), "str"
    if vals.dtype.kind == "b":
        return vals.astype(np.uint8), "bool"
    return vals, vals.dtype.kind


def _decode_column(ds, kind: str) -> np.ndarray:
    if kind == "ragged":
        flat = ds["values"][()]
        offsets = ds["offsets"][()]
        out = np.empty(len(offsets) - 1, dtype=object)
        for i in range(len(out)):
            out[i] = flat[offsets[i] : offsets[i + 1]]
        return out
    data = ds[()]
    if kind == "fstr":
        out = np.char.decode(data, "utf-8").astype(object)
        return np.where(out == _NULL, None, out)
    if kind == "str":
        out = np.array([v.decode() if isinstance(v, bytes) else str(v) for v in data], dtype=object)
        return np.where(out == _NULL, None, out)
    if kind == "bool":
        return data.astype(bool)
    return data


def write_hdf(df: pd.DataFrame, path: str, key: str, mode: str = "a") -> None:
    """Write one DataFrame under ``key`` (pandas ``df.to_hdf`` surface)."""
    with h5py.File(path, mode) as f:
        if key in f:
            del f[key]
        g = f.create_group(key)
        g.attrs[_FORMAT_ATTR] = 1
        kinds: dict[str, str] = {}
        names = [str(c) for c in df.columns]
        g.attrs["columns"] = json.dumps(names)
        # non-trivial index is preserved as a pseudo-column
        idx = df.index
        if not (isinstance(idx, pd.RangeIndex) and idx.start == 0 and idx.step == 1):
            raw = idx.to_numpy()
            if raw.dtype.kind not in "biufc":
                raw = raw.astype(object)
            ivals, ikind = _encode_column(raw)
            kinds["__index__"] = ikind
            _write_ds(g, "__index__", ivals)
        for col, name in zip(df.columns, names):
            vals = df[col].to_numpy()
            data, kind = _encode_column(vals)
            kinds[name] = kind
            _write_ds(g, name, data)
        g.attrs["kinds"] = json.dumps(kinds)


def _write_ds(g: h5py.Group, name: str, data) -> None:
    if isinstance(data, tuple):  # ragged: (flat values, offsets)
        sub = g.create_group(name)
        sub.create_dataset("values", data=data[0])
        sub.create_dataset("offsets", data=data[1])
        return
    if data.dtype == object:
        dt = h5py.string_dtype(encoding="utf-8")
        g.create_dataset(name, data=data.astype(dt), dtype=dt)
    else:
        g.create_dataset(name, data=data)


def _is_pytables_frame(g) -> bool:
    return (isinstance(g, h5py.Group)
            and g.attrs.get("pandas_type", b"") in (b"frame", "frame"))


def _is_frame_group(g) -> bool:
    """A group this store can decode: native layout or pytables frame —
    the single recognition rule list_keys and read_hdf("all") share."""
    return isinstance(g, h5py.Group) and (
        _FORMAT_ATTR in g.attrs or _is_pytables_frame(g))


def _read_pytables_frame(g: h5py.Group) -> pd.DataFrame:
    """Decode a pandas ``to_hdf(format='fixed')`` frame written by the
    REFERENCE stack (pytables) — every tabular artifact the reference
    persists uses this layout (evaluate_concordance.py:101-105 etc.), so
    a user migrating an existing workflow can read their h5 files without
    pytables installed. Layout: ``axis0`` = columns, ``axis1`` = index,
    ``blockN_items``/``blockN_values`` per dtype block. pandas writes
    block values TRANSPOSED (``transposed`` attr, (n_rows, n_items) on
    disk), stores pure-string columns as fixed-width 'S' arrays in the
    file's declared encoding, and mixed-object blocks as ONE pickled
    ndarray in a VLArray (the same pickle trust model as the reference's
    own model registry)."""
    import pickle

    encoding = g.attrs.get("encoding", b"utf-8")
    encoding = encoding.decode() if isinstance(encoding, bytes) else str(encoding)

    def to_str(v):
        return v.decode(encoding, "replace") if isinstance(v, bytes) else v

    def arr(ds):
        a = ds[:]
        if a.dtype == object or ds.attrs.get("PSEUDOATOM") is not None:
            parts = [pickle.loads(bytes(bytearray(e))) for e in a]
            a = np.asarray(parts[0] if len(parts) == 1 else np.concatenate(parts))
        if ds.attrs.get("transposed", False):
            a = a.T
        return a

    def destring(col: np.ndarray) -> np.ndarray:
        if col.dtype.kind == "S" or (
                col.dtype == object and len(col) and isinstance(col[0], bytes)):
            return np.asarray([to_str(v) for v in col], dtype=object)
        return col

    nblocks = int(g.attrs.get("nblocks", 0))
    order = [to_str(x) for x in g["axis0"][:]]
    idx = arr(g["axis1"]) if "axis1" in g else np.empty(0)
    n_rows = len(idx)
    cols: dict = {}
    for b in range(nblocks):
        items = [to_str(x) for x in g[f"block{b}_items"][:]]
        values = arr(g[f"block{b}_values"])  # (n_items, n_rows) after un-transpose
        if values.ndim != 2:
            values = values.reshape(len(items), -1)
        for j, name in enumerate(items):
            # an empty frame stores (1, 1) placeholder blocks: every
            # column is empty regardless of the stored atom
            col = values[j, :n_rows] if j < values.shape[0] and n_rows else \
                np.empty(0, dtype=values.dtype)
            cols[name] = destring(np.asarray(col))
    df = pd.DataFrame({name: cols[name] for name in order if name in cols})
    if n_rows == len(df):
        df.index = [to_str(v) for v in idx]
    return df


def _read_frame(g: h5py.Group) -> pd.DataFrame:
    if _FORMAT_ATTR not in g.attrs and _is_pytables_frame(g):
        return _read_pytables_frame(g)
    kinds = json.loads(g.attrs["kinds"])
    names = json.loads(g.attrs["columns"])
    cols = {}
    for name in names:
        cols[name] = _decode_column(g[name], kinds.get(name, "f"))
    df = pd.DataFrame(cols)
    if "__index__" in g:
        df.index = _decode_column(g["__index__"], kinds.get("__index__", "f"))
    return df


def list_keys(path: str) -> list[str]:
    with h5py.File(path, "r") as f:
        return sorted(k for k in f.keys() if _is_frame_group(f[k]))


def read_hdf(path: str, key: str = "all", skip_keys: list[str] | None = None, columns_subset=None) -> pd.DataFrame:
    """Read one key, or concat every stored key when ``key="all"`` is absent.

    Mirrors ugbio_core.h5_utils.read_hdf as used by evaluate_concordance.py:
    82-87 — the "all" pseudo-key concatenates per-chromosome frames, minus
    ``skip_keys``.
    """
    skip = set(skip_keys or [])
    with h5py.File(path, "r") as f:
        if key in f and key not in ("all",):
            df = _read_frame(f[key])
        elif key == "all" and "all" in f:
            df = _read_frame(f["all"])
        elif key == "all":
            frames = [
                _read_frame(f[k])
                for k in sorted(f.keys())
                if k not in skip and _is_frame_group(f[k])
            ]
            if not frames:
                raise KeyError(f"no frames in {path}")
            df = pd.concat(frames, ignore_index=False)
        else:
            raise KeyError(f"key {key!r} not in {path}")
    if columns_subset is not None:
        df = df[[c for c in columns_subset if c in df.columns]]
    return df
