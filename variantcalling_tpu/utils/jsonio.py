"""ONE JSON-emission helper for every CLI that dumps JSON.

``vctpu knobs --json`` used to hand-roll its dump and ``vctpu obs
summary --json`` would have been the second copy; both now render
through :func:`render_json` / :func:`emit_json` so the CLI JSON surface
has one formatting contract (2-space indent, insertion order preserved,
trailing newline) and one place to change it.
"""

from __future__ import annotations

import json
import sys


def render_json(obj, indent: int = 2) -> str:
    """The canonical CLI JSON rendering (no trailing newline)."""
    return json.dumps(obj, indent=indent)


def emit_json(obj, stream=None) -> None:
    """Print ``obj`` as canonical CLI JSON (with trailing newline)."""
    print(render_json(obj), file=stream if stream is not None else sys.stdout)
