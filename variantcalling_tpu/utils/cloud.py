"""Cloud object localization (gs:// s3:// -> local files).

Reference surface: ugbio_cloud_utils.cloud_sync / optional_cloud_sync
(imported at coverage_analysis.py:46, quick_fingerprinter.py:6; internals
in the missing submodule). Local paths pass through untouched; remote URIs
are localized into a cache directory via the gsutil/gcloud/aws CLIs when
present. This framework runs in zero-egress environments, so failure modes
are explicit: ``cloud_sync`` raises, ``optional_cloud_sync`` returns the
URI unchanged (callers that can stream it themselves may still proceed).
"""

from __future__ import annotations

import os
import subprocess

DEFAULT_CACHE = os.path.join(os.path.expanduser("~"), ".cache", "vctpu_cloud")


def is_remote(path: str) -> bool:
    return str(path).startswith(("gs://", "s3://"))


def _local_target(uri: str, cache_dir: str) -> str:
    scheme, rest = uri.split("://", 1)
    return os.path.join(cache_dir, scheme, rest)


DOWNLOAD_TIMEOUT_S = int(os.environ.get("VCTPU_CLOUD_TIMEOUT", "600"))


def cloud_sync(uri: str, cache_dir: str = DEFAULT_CACHE, force: bool = False) -> str:
    """Localize a gs:// or s3:// object; local paths pass through."""
    if not is_remote(uri):
        return uri
    target = _local_target(uri, cache_dir)
    if os.path.exists(target) and not force:
        return target
    os.makedirs(os.path.dirname(target), exist_ok=True)
    tmp = target + ".part"
    if uri.startswith("gs://"):
        cmds = [["gsutil", "-q", "cp", uri, tmp], ["gcloud", "storage", "cp", uri, tmp]]
    else:
        cmds = [["aws", "s3", "cp", "--quiet", uri, tmp]]
    last_err: Exception | None = None
    for cmd in cmds:
        try:
            subprocess.run(cmd, check=True, capture_output=True, timeout=DOWNLOAD_TIMEOUT_S)
            os.replace(tmp, target)
            return target
        except (OSError, subprocess.SubprocessError) as e:  # tool missing / copy failed / hung
            last_err = e
    raise RuntimeError(f"could not localize {uri}: no working cloud CLI ({last_err})")


def optional_cloud_sync(uri: str, cache_dir: str = DEFAULT_CACHE) -> str:
    """cloud_sync that degrades to returning the URI unchanged."""
    try:
        return cloud_sync(uri, cache_dir)
    except RuntimeError:
        return uri
