"""Cloud object localization (gs:// s3:// -> local files).

Reference surface: ugbio_cloud_utils.cloud_sync / optional_cloud_sync
(imported at coverage_analysis.py:46, quick_fingerprinter.py:6; internals
in the missing submodule). Local paths pass through untouched; remote URIs
are localized into a cache directory via the gsutil/gcloud/aws CLIs when
present. This framework runs in zero-egress environments, so failure modes
are explicit: ``cloud_sync`` raises, ``optional_cloud_sync`` returns the
URI unchanged (callers that can stream it themselves may still proceed).
"""

from __future__ import annotations

import os
import subprocess

from variantcalling_tpu import knobs, logger

DEFAULT_CACHE = os.path.join(os.path.expanduser("~"), ".cache", "vctpu_cloud")


def is_remote(path: str) -> bool:
    return str(path).startswith(("gs://", "s3://"))


def _local_target(uri: str, cache_dir: str) -> str:
    scheme, rest = uri.split("://", 1)
    return os.path.join(cache_dir, scheme, rest)


def cloud_sync(uri: str, cache_dir: str = DEFAULT_CACHE, force: bool = False) -> str:
    """Localize a gs:// or s3:// object; local paths pass through."""
    if not is_remote(uri):
        return uri
    target = _local_target(uri, cache_dir)
    if os.path.exists(target) and not force:
        return target
    os.makedirs(os.path.dirname(target), exist_ok=True)
    tmp = target + ".tmp"
    if uri.startswith("gs://"):
        cmds = [["gsutil", "-q", "cp", uri, tmp], ["gcloud", "storage", "cp", uri, tmp]]
    else:
        cmds = [["aws", "s3", "cp", "--quiet", uri, tmp]]
    last_err: Exception | None = None
    for cmd in cmds:
        try:
            subprocess.run(cmd, check=True, capture_output=True,
                           timeout=knobs.get_int("VCTPU_CLOUD_TIMEOUT"))
            os.replace(tmp, target)
            return target
        except (OSError, subprocess.SubprocessError) as e:  # tool missing / copy failed / hung
            last_err = e
    raise RuntimeError(f"could not localize {uri}: no working cloud CLI ({last_err})")


def optional_cloud_sync(uri: str, cache_dir: str = DEFAULT_CACHE) -> str:
    """cloud_sync that degrades to returning the URI unchanged — loudly:
    the caller may be able to stream the URI itself, but the operator
    should know localization failed rather than discover a slow or
    failing remote read later."""
    try:
        return cloud_sync(uri, cache_dir)
    except RuntimeError as e:
        logger.warning("cloud localization failed, passing URI through: %s", e)
        return uri


# ---------------------------------------------------------------------------
# GCS OAuth token mint (reference ugvc/utils/cloud_auth.py:17-45)
# ---------------------------------------------------------------------------

GOOGLE_APPLICATION_CREDENTIALS = "GOOGLE_APPLICATION_CREDENTIALS"
GCS_OAUTH_TOKEN = "GCS_OAUTH_TOKEN"
_GCS_SCOPE = "https://www.googleapis.com/auth/devstorage.read_only"


def get_gcs_token(verify: bool = False) -> str:
    """Mint (or pass through) a GCS access token.

    Mirrors the reference contract: with GOOGLE_APPLICATION_CREDENTIALS set,
    mint + refresh through google.auth; else fall back to a pre-existing
    GCS_OAUTH_TOKEN; else raise. ``verify=True`` additionally checks token
    liveness against the oauth2 tokeninfo endpoint (the reference always
    POSTs; here it is opt-in because this framework targets zero-egress
    environments where the mint itself is offline but verification is not).
    """
    if GOOGLE_APPLICATION_CREDENTIALS in os.environ:
        from google.auth import default
        from google.auth.transport.requests import Request

        credentials, _project = default(scopes=[_GCS_SCOPE])
        credentials.refresh(Request())
        token = credentials.token
    elif GCS_OAUTH_TOKEN in os.environ:
        token = os.environ[GCS_OAUTH_TOKEN]
    else:
        raise ValueError(
            f"Could not generate gcs token: set {GOOGLE_APPLICATION_CREDENTIALS} "
            f"(to mint) or {GCS_OAUTH_TOKEN} (pre-existing token)"
        )
    if verify:
        import requests

        resp = requests.post(
            "https://www.googleapis.com/oauth2/v1/tokeninfo",
            data=f"access_token={token}",
            headers={"content-type": "application/x-www-form-urlencoded"},
            timeout=30,
        )
        if not resp.ok:
            raise ValueError(f"Could not verify token: {resp.text}")
        if not resp.json().get("expires_in", 0) > 0:
            raise ValueError("token expired")
    return token
