"""Persistent XLA compilation cache for every CLI entry point.

The flagship pipelines are CLI tools invoked once per file (reference
docs/howto-callset-filter.md's per-callset invocations), so without a
persistent cache each process re-pays the full jit compile of the fused
featurize+score program (~4s on CPU, 20-40s first-compile on TPU through
the tunnel) before touching a single variant. JAX's compilation cache
persists compiled executables on disk keyed by (HLO, jaxlib, flags,
device kind); warm CLI invocations then deserialize in ~0.1-0.5s.

Cache location: ``$VCTPU_COMPILE_CACHE`` if set (empty string disables),
else ``~/.cache/vctpu/xla``. Enabling is idempotent and never fatal — a
read-only home directory simply leaves caching off.

Note: XLA:CPU logs a benign machine-feature mismatch (E-level,
``+prefer-no-scatter``/``+prefer-no-gather``) when loading AOT results;
these are XLA-internal pseudo-features, not real ISA bits. We leave
stderr untouched — suppressing C++ E-logs would also hide real faults.
"""

from __future__ import annotations

import os
import sys

_ENABLED = False


def enable_persistent_cache() -> bool:
    """Point JAX's compilation cache at a persistent directory; returns
    True when enabled (idempotent).

    When jax is not imported yet (the CLI dispatch fast path — many tools
    are pandas-only and must not pay a jax import at startup), the cache
    is configured through JAX's environment knobs, which jax reads at
    import time; only an already-imported jax needs config.update."""
    global _ENABLED
    if _ENABLED:
        return True
    from variantcalling_tpu import knobs

    path = knobs.get_str("VCTPU_COMPILE_CACHE")
    if path == "":
        return False
    if path is None:
        path = os.path.join(os.path.expanduser("~"), ".cache", "vctpu", "xla")
    try:
        os.makedirs(path, exist_ok=True)
        if "jax" not in sys.modules:
            os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", path)
            # the fused pipeline programs compile in 1-5s; cache anything
            # that takes meaningful time so warm CLI runs skip it
            os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.5")
        else:
            import jax

            jax.config.update("jax_compilation_cache_dir", path)
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception as e:  # noqa: BLE001 — caching is best-effort, never fatal
        from variantcalling_tpu.utils import degrade

        degrade.record("compile_cache.enable", e,
                       fallback="persistent XLA cache disabled", warn=True)
        return False
    _ENABLED = True
    return True
