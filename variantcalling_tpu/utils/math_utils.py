"""Scalar/numpy math helpers with the reference API surface.

Parity target: ``ugvc/utils/math_utils.py`` (reference ``/root/reference``).
Device-batched equivalents live in :mod:`variantcalling_tpu.ops.math`; these
host-side versions keep the exact call signatures so pipeline code and tests
can run without a device.
"""

from __future__ import annotations

import numpy as np


def safe_divide(numerator: float, denominator: float, return_if_denominator_is_0: float = 0):
    """numerator/denominator, or ``return_if_denominator_is_0`` when denominator == 0.

    Parity: ugvc/utils/math_utils.py:9-28.
    """
    if denominator == 0:
        return return_if_denominator_is_0
    return numerator / denominator


def phred(p) -> np.ndarray:
    """Probabilities -> Phred quality scores (-10*log10 p). Parity: math_utils.py:31-47."""
    return -10 * np.log10(np.asarray(p, dtype=float))


def unphred(q):
    """Phred quality scores -> probabilities. Parity: math_utils.py:67-84."""
    if isinstance(q, float):
        return 10 ** (-q / 10)
    return np.power(10.0, -np.asarray(q, dtype=float) / 10)


def phred_str(p) -> str:
    """Error probabilities -> phred+33 encoded string. Parity: math_utils.py:50-64."""
    q = phred(p)
    return "".join(chr(int(x) + 33) for x in q)


def unphred_str(strq: str) -> np.ndarray:
    """Phred+33 string -> error probabilities. Parity: math_utils.py:87-101."""
    q = [ord(x) - 33 for x in strq]
    return unphred(q)
