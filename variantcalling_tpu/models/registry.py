"""Model container: named-model pickles with the reference's naming scheme.

The reference's ``train_models_pipeline`` dumps ``<prefix>.pkl`` holding
multiple named models — {rf, threshold} × {ignore_gt} × {incl/excl hpol
runs} (names observed at docs/howto-callset-filter.md:114,139 and
test_vc_report.py:23). This registry keeps that contract: a dict-like
pickle ``{model_name: model}`` where model is a FlatForest, ThresholdModel,
or a fitted sklearn classifier (converted to FlatForest on load).
"""

from __future__ import annotations

import os
import pickle

from variantcalling_tpu.models.dan import DanModel
from variantcalling_tpu.models.forest import FlatForest, from_sklearn
from variantcalling_tpu.models.threshold import ThresholdModel

MODEL_NAME_PATTERN = "{family}_model_{gt}_{hpol}"  # e.g. rf_model_ignore_gt_incl_hpol_runs

# Model-family resolution (docs/models.md). "forest" covers every
# tree-shaped scorer (FlatForest and anything _coerce turns into one);
# the name prefixes in MODEL_NAME_PATTERN map onto these families.
FAMILIES = ("forest", "threshold", "dan")
_NAME_PREFIX_FAMILY = {"rf": "forest", "xgb": "forest",
                       "threshold": "threshold", "dan": "dan"}


def family_of(model: object) -> str:
    """The scoring family a loaded model belongs to — the single
    spelling used by FilterContext resolution, provenance headers and
    the scoring identity."""
    if isinstance(model, DanModel):
        return "dan"
    if isinstance(model, ThresholdModel):
        return "threshold"
    return "forest"


def family_of_name(model_name: str) -> str | None:
    """Family implied by a registry model name (``rf_model_...`` →
    forest), or None when the name follows no known pattern."""
    prefix = model_name.split("_model_", 1)[0] if "_model_" in model_name else model_name
    return _NAME_PREFIX_FAMILY.get(prefix)


def standard_model_names(families=("rf", "threshold")) -> list[str]:
    names = []
    for fam in families:
        for gt in ("ignore_gt", "use_gt"):
            for hpol in ("incl_hpol_runs", "excl_hpol_runs"):
                names.append(MODEL_NAME_PATTERN.format(family=fam, gt=gt, hpol=hpol))
    return names


def save_models(path: str, models: dict[str, object]) -> None:
    """Atomic write (tmp + rename): a crash mid-write must never leave a
    truncated pickle — checkpoint consumers resume from this file."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as fh:
        pickle.dump(models, fh)
    os.replace(tmp, path)


def load_models(path: str) -> dict[str, object]:
    if path.endswith(".json"):
        # bare xgboost JSON model file (Booster.save_model output)
        from variantcalling_tpu.models.xgb import from_xgboost_json

        return {"model": from_xgboost_json(path)}
    with open(path, "rb") as fh:
        models = pickle.load(fh)
    if not isinstance(models, dict):
        models = {"model": models}
    if isinstance(models.get("learner"), dict) and "gradient_booster" in models["learner"]:
        # the pickle IS one parsed xgboost JSON model, not a name->model map
        from variantcalling_tpu.models.xgb import from_xgboost_json

        return {"model": from_xgboost_json(models)}
    return {k: _coerce(v) for k, v in models.items()}


def load_model(path: str, model_name: str) -> object:
    models = load_models(path)
    if model_name not in models:
        # Name the missing FAMILY, not just the key: a family-explicit
        # run (VCTPU_MODEL_FAMILY=dan against a forest-only pickle) must
        # say which family the file lacks, not raise a bare KeyError.
        requested = family_of_name(model_name)
        present = sorted({family_of(m) for m in models.values()})
        hint = ""
        if requested is not None and requested not in present:
            hint = (f"; no {requested!r}-family model in this file "
                    f"(families present: {present})")
        raise KeyError(
            f"model {model_name!r} not in {sorted(models)} (file: {path}){hint}")
    return models[model_name]


def _coerce(model: object) -> object:
    if isinstance(model, (FlatForest, ThresholdModel, DanModel)):
        return model
    from variantcalling_tpu.models.xgb import from_xgboost, from_xgboost_json, looks_like_xgboost

    if looks_like_xgboost(model):
        # XGBClassifier / Booster pickle — unpicklable only when xgboost is
        # importable, in which case its own JSON dump is the exact source
        return from_xgboost(model)
    if isinstance(model, dict) and "learner" in model:
        return from_xgboost_json(model)
    if hasattr(model, "tree_") or hasattr(model, "estimators_"):
        # the fitted column order MUST ride along: the pipeline reorders
        # model features onto its own feature layout by NAME, and a
        # nameless forest scores positionally against the wrong columns
        fni = getattr(model, "feature_names_in_", None)
        return from_sklearn(model, feature_names=None if fni is None else list(fni))
    return model
