"""Decision-forest inference as vmap'd node-gather traversal on TPU.

The reference's hot loop is sklearn RandomForest / xgboost ``predict_proba``
over ~5M variants on CPU (docs/howto-callset-filter.md:63,114; BASELINE
north_star). Here a trained forest is flattened into dense per-tree node
arrays and traversal is ``max_depth`` rounds of batched gathers — fully
vectorized over (variants × trees), jit/pjit-safe, and shardable along the
variants axis. Works for both class-probability forests (RF: mean of leaf
probabilities) and boosted margins (GBT: sum + sigmoid).
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace

import numpy as np

import jax
import jax.numpy as jnp

LEAF = -1


@dataclass
class FlatForest:
    """Dense forest: (n_trees, max_nodes) arrays; leaves self-loop with feature=LEAF."""

    feature: np.ndarray  # int32 (T, M); LEAF for leaf nodes
    threshold: np.ndarray  # float32 (T, M)
    left: np.ndarray  # int32 (T, M)
    right: np.ndarray  # int32 (T, M)
    value: np.ndarray  # float32 (T, M): leaf payload (class-1 prob or margin)
    max_depth: int
    aggregation: str = "mean"  # "mean" (RF proba) | "logit_sum" (GBT margin)
    base_score: float = 0.0  # added before sigmoid for logit_sum
    feature_names: list[str] = field(default_factory=list)
    pass_threshold: float = 0.5  # TREE_SCORE >= this -> PASS

    @property
    def n_trees(self) -> int:
        return self.feature.shape[0]

    def astuple(self):
        return (
            jnp.asarray(self.feature),
            jnp.asarray(self.threshold),
            jnp.asarray(self.left),
            jnp.asarray(self.right),
            jnp.asarray(self.value),
        )


def predict_score(forest: FlatForest, x: jnp.ndarray) -> jnp.ndarray:
    """TREE_SCORE in [0,1] for a (N, F) feature matrix (jit-safe).

    Traversal: ``max_depth`` rounds of gathers; each round every (variant,
    tree) pair advances one level (leaves self-loop), so control flow is
    static and XLA lowers the whole forest to fused gathers — no
    per-variant Python, no host sync.
    """
    feat, thr, left, right, value = forest.astuple()
    n = x.shape[0]
    t = feat.shape[0]
    tree_ids = jnp.arange(t)[None, :]  # (1, T)

    def body(_, idx):
        f = feat[tree_ids, idx]  # (N, T)
        th = thr[tree_ids, idx]
        xv = jnp.take_along_axis(x, jnp.maximum(f, 0), axis=1)  # (N, T)
        nxt = jnp.where(xv <= th, left[tree_ids, idx], right[tree_ids, idx])
        return jnp.where(f == LEAF, idx, nxt)

    idx0 = jnp.zeros((n, t), dtype=jnp.int32)
    idx = jax.lax.fori_loop(0, forest.max_depth, body, idx0)
    leaf_vals = value[tree_ids, idx]  # (N, T)
    if forest.aggregation == "mean":
        return jnp.mean(leaf_vals, axis=1)
    if forest.aggregation == "logit_sum":
        return jax.nn.sigmoid(jnp.sum(leaf_vals, axis=1) + forest.base_score)
    raise ValueError(f"unknown aggregation {forest.aggregation!r}")


def from_sklearn(clf, feature_names: list[str] | None = None, pass_threshold: float = 0.5) -> FlatForest:
    """Flatten a fitted sklearn RandomForestClassifier/DecisionTree ensemble.

    Faithful to sklearn semantics: split is ``x[f] <= threshold`` goes left
    (sklearn uses <=); leaf value = class-1 fraction of training samples in
    the leaf; prediction = mean over trees (predict_proba).
    """
    raw = getattr(clf, "estimators_", None)
    if raw is None:
        estimators = [clf]
    elif isinstance(raw, np.ndarray):
        # GradientBoosting stores an (n_stages, n_classes) ndarray of
        # regressor trees -> boosted-margin aggregation, not mean-proba
        if raw.ndim == 2 and raw.shape[1] != 1:
            raise ValueError("only binary-class boosted ensembles are supported")
        return _from_sklearn_gbt(clf, raw.ravel().tolist(), feature_names, pass_threshold)
    else:
        estimators = list(raw)
    n_nodes = [e.tree_.node_count for e in estimators]
    m = max(n_nodes)
    t = len(estimators)
    feature = np.full((t, m), LEAF, dtype=np.int32)
    threshold = np.zeros((t, m), dtype=np.float32)
    left = np.zeros((t, m), dtype=np.int32)
    right = np.zeros((t, m), dtype=np.int32)
    value = np.zeros((t, m), dtype=np.float32)
    max_depth = 1
    for ti, est in enumerate(estimators):
        tr = est.tree_
        nc = tr.node_count
        f = tr.feature.astype(np.int32)
        is_leaf = tr.children_left == -1
        feature[ti, :nc] = np.where(is_leaf, LEAF, f)
        # sklearn compares float32-cast x against float64 thresholds; storing
        # the largest f32 <= threshold keeps `x <= thr` decisions bit-identical
        thr64 = tr.threshold
        thr32 = thr64.astype(np.float32)
        too_big = thr32.astype(np.float64) > thr64
        thr32[too_big] = np.nextafter(thr32[too_big], np.float32(-np.inf))
        threshold[ti, :nc] = thr32
        node_ids = np.arange(nc, dtype=np.int32)
        left[ti, :nc] = np.where(is_leaf, node_ids, tr.children_left)
        right[ti, :nc] = np.where(is_leaf, node_ids, tr.children_right)
        counts = tr.value[:, 0, :]  # (nc, n_classes) — class sample fractions
        if counts.shape[1] == 2:
            denom = counts.sum(axis=1)
            value[ti, :nc] = np.where(denom > 0, counts[:, 1] / np.maximum(denom, 1e-12), 0.0)
        else:
            # degenerate single-class fit: every leaf predicts that class
            classes = getattr(est, "classes_", getattr(clf, "classes_", np.array([1])))
            value[ti, :nc] = 1.0 if classes[0] == 1 else 0.0
        max_depth = max(max_depth, int(tr.max_depth))
    return FlatForest(
        feature=feature,
        threshold=threshold,
        left=left,
        right=right,
        value=value,
        max_depth=max_depth,
        aggregation="mean",
        feature_names=feature_names or [],
        pass_threshold=pass_threshold,
    )


def _from_sklearn_gbt(clf, trees: list, feature_names: list[str] | None, pass_threshold: float) -> FlatForest:
    """Flatten a fitted binary GradientBoostingClassifier.

    score = sigmoid(init_log_odds + lr * sum(tree margins)) — matches
    sklearn's staged decision function for the log-loss binary case.
    """
    lr = float(getattr(clf, "learning_rate", 1.0))
    base = 0.0
    init = getattr(clf, "init_", None)
    if init is not None and hasattr(init, "class_prior_"):
        p1 = float(np.clip(init.class_prior_[-1], 1e-12, 1 - 1e-12))
        base = float(np.log(p1 / (1 - p1)))
    m = max(t.tree_.node_count for t in trees)
    t_n = len(trees)
    feature = np.full((t_n, m), LEAF, dtype=np.int32)
    threshold = np.zeros((t_n, m), dtype=np.float32)
    left = np.zeros((t_n, m), dtype=np.int32)
    right = np.zeros((t_n, m), dtype=np.int32)
    value = np.zeros((t_n, m), dtype=np.float32)
    max_depth = 1
    for ti, est in enumerate(trees):
        tr = est.tree_
        nc = tr.node_count
        is_leaf = tr.children_left == -1
        feature[ti, :nc] = np.where(is_leaf, LEAF, tr.feature.astype(np.int32))
        thr64 = tr.threshold
        thr32 = thr64.astype(np.float32)
        too_big = thr32.astype(np.float64) > thr64
        thr32[too_big] = np.nextafter(thr32[too_big], np.float32(-np.inf))
        threshold[ti, :nc] = thr32
        node_ids = np.arange(nc, dtype=np.int32)
        left[ti, :nc] = np.where(is_leaf, node_ids, tr.children_left)
        right[ti, :nc] = np.where(is_leaf, node_ids, tr.children_right)
        value[ti, :nc] = lr * tr.value[:, 0, 0]
        max_depth = max(max_depth, int(tr.max_depth))
    return FlatForest(
        feature=feature,
        threshold=threshold,
        left=left,
        right=right,
        value=value,
        max_depth=max_depth,
        aggregation="logit_sum",
        base_score=base,
        feature_names=feature_names or [],
        pass_threshold=pass_threshold,
    )


def with_feature_order(forest: FlatForest, feature_names: list[str]) -> FlatForest:
    """Remap node feature indices to a new feature-column order."""
    if not forest.feature_names or forest.feature_names == feature_names:
        return forest
    mapping = np.asarray([feature_names.index(f) for f in forest.feature_names], dtype=np.int32)
    new_feat = np.where(forest.feature == LEAF, LEAF, mapping[np.maximum(forest.feature, 0)])
    return replace(forest, feature=new_feat.astype(np.int32), feature_names=list(feature_names))
