"""Decision-forest inference as vmap'd node-gather traversal on TPU.

The reference's hot loop is sklearn RandomForest / xgboost ``predict_proba``
over ~5M variants on CPU (docs/howto-callset-filter.md:63,114; BASELINE
north_star). Here a trained forest is flattened into dense per-tree node
arrays and traversal is ``max_depth`` rounds of batched gathers — fully
vectorized over (variants × trees), jit/pjit-safe, and shardable along the
variants axis. Works for both class-probability forests (RF: mean of leaf
probabilities) and boosted margins (GBT: sum + sigmoid).
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace

import numpy as np

import jax
import jax.numpy as jnp

LEAF = -1


@dataclass
class FlatForest:
    """Dense forest: (n_trees, max_nodes) arrays; leaves self-loop with feature=LEAF."""

    feature: np.ndarray  # int32 (T, M); LEAF for leaf nodes
    threshold: np.ndarray  # float32 (T, M)
    left: np.ndarray  # int32 (T, M)
    right: np.ndarray  # int32 (T, M)
    value: np.ndarray  # float32 (T, M): leaf payload (class-1 prob or margin)
    max_depth: int
    aggregation: str = "mean"  # "mean" (RF proba) | "logit_sum" (GBT margin)
    base_score: float = 0.0  # added before sigmoid for logit_sum
    feature_names: list[str] = field(default_factory=list)
    pass_threshold: float = 0.5  # TREE_SCORE >= this -> PASS
    # xgboost-style missing-value routing: NaN features take the node's
    # default branch. None = no missing routing (NaN routes right, since
    # all NaN comparisons are false) — sklearn/boosting models never see
    # NaN (host columns are nan_to_num'd), so the hot path stays free of
    # the extra gather.
    default_left: np.ndarray | None = None  # bool (T, M) or None

    @property
    def n_trees(self) -> int:
        return self.feature.shape[0]


def sequential_tree_sum(per_tree: jnp.ndarray) -> jnp.ndarray:
    """(N, T) per-tree leaf margins -> (N,) canonical-order sum.

    THE one reduction every inference strategy (gather walk, scan GEMM,
    wide GEMM, pallas) funnels through: a loop-carried fori_loop over
    trees t=0,1,...,T-1. XLA cannot reassociate a loop-carried f32 sum,
    and the native C++ walk accumulates in the same order, so any path
    that produces bit-exact per-tree leaf values produces bit-identical
    margins (the round-5 multihost byte-parity fix, see predict_margin).
    """
    n, t = per_tree.shape

    def acc_body(ti, acc):
        return acc + per_tree[:, ti]

    return jax.lax.fori_loop(0, t, acc_body,
                             jnp.zeros(n, dtype=per_tree.dtype))


def _packed_node_table(forest: FlatForest) -> np.ndarray:
    """(T*M, C) float32 packed node table for the gather walk: columns
    [feature, threshold, left, right, value(, default_left)] with the
    int32 columns BITCAST into the f32 lanes (a gather only moves bytes,
    so the bitcast round-trip is exact). One table -> ONE gather per
    traversal level instead of four or five — on XLA:CPU each rank-2
    gather lowers to its own scalar loop nest, and collapsing them (plus
    flattening the (T, M) indexing into 1-D takes) measured ~2.5x on the
    gather strategy (docs/perf_notes.md "Closing the XLA:CPU gather
    gap"). Built at trace time from host arrays, so it lands in the
    compiled program as one constant.
    """
    def i32_as_f32(a):
        # np.asarray first: boosting-trained forests hold concrete jax
        # arrays, whose .astype lacks numpy's .view
        return np.asarray(a, dtype=np.int32).reshape(-1).view(np.float32)

    cols = [
        i32_as_f32(forest.feature),
        np.asarray(forest.threshold, dtype=np.float32).reshape(-1),
        i32_as_f32(forest.left),
        i32_as_f32(forest.right),
        np.asarray(forest.value, dtype=np.float32).reshape(-1),
    ]
    if forest.default_left is not None:
        cols.append(np.asarray(forest.default_left,
                               dtype=np.float32).reshape(-1))
    return np.stack(cols, axis=1)


def predict_margin(forest: FlatForest, x: jnp.ndarray) -> jnp.ndarray:
    """Raw per-variant leaf-value SUM in canonical tree order (jit-safe).

    Traversal: ``max_depth`` rounds of gathers; each round every (variant,
    tree) pair advances one level (leaves self-loop), so control flow is
    static — no per-variant Python, no host sync. Each round makes ONE
    gather of the packed node table (:func:`_packed_node_table`) with
    flat 1-D node ids, plus one flat take of the feature matrix — the
    XLA:CPU-friendly lowering (the naive per-array ``take_along_axis``
    formulation ran ~2.5x slower; docs/perf_notes.md). Flat int32
    indexing bounds N*F and T*M to 2^31 — callers chunk the variants
    axis (CHUNK = 2^18) far below that.

    The accumulation is a SEQUENTIAL fori_loop over trees (t=0,1,...,T-1)
    rather than ``jnp.sum``: XLA's reduce reassociates f32 sums into
    SIMD-lane partials whose grouping varies with backend and device
    count, which made jit scores differ from the native C++ walk (and
    from themselves across mesh shapes) by 1 ulp — the round-5 multihost
    byte-parity flake. A loop-carried dependency cannot be reassociated,
    and the native walk accumulates in the same order
    (``native/src/vctpu_forest_tile.h`` forest_walk_tile), so the two
    engines' sums are bit-identical (tests/unit/test_engine_contract.py).
    """
    t, m = forest.feature.shape
    has_dl = forest.default_left is not None
    ptab = jnp.asarray(_packed_node_table(forest))
    n = x.shape[0]
    xflat = jnp.asarray(x).reshape(-1)
    fbase = (jnp.arange(n, dtype=jnp.int32) * x.shape[1])[:, None]  # (N, 1)
    toff = (jnp.arange(t, dtype=jnp.int32) * m)[None, :]  # (1, T)

    def unpack_i32(col):
        return jax.lax.bitcast_convert_type(col, jnp.int32)

    def body(_, idx):
        rows = ptab[toff + idx]  # (N, T, C): the ONE node gather per level
        f = unpack_i32(rows[..., 0])
        th = rows[..., 1]
        xv = xflat[fbase + jnp.maximum(f, 0)]  # (N, T)
        go_left = xv <= th
        if has_dl:  # missing (NaN) takes the node's default branch
            go_left = jnp.where(jnp.isnan(xv), rows[..., 5] != 0, go_left)
        nxt = jnp.where(go_left, unpack_i32(rows[..., 2]),
                        unpack_i32(rows[..., 3]))
        return jnp.where(f == LEAF, idx, nxt)

    idx0 = jnp.zeros((n, t), dtype=jnp.int32)
    idx = jax.lax.fori_loop(0, forest.max_depth, body, idx0)
    leaf_vals = ptab[toff + idx][..., 4]  # (N, T)
    return sequential_tree_sum(leaf_vals)


def finalize_margin(margin: np.ndarray, forest: FlatForest) -> np.ndarray:
    """SHARED host finalization margin -> TREE_SCORE — the single place
    that turns a canonical-order leaf sum into the score, used by BOTH
    scoring engines so the final bits cannot depend on the engine.

    ``mean`` divides (IEEE division is correctly rounded, so either side
    could do it); ``logit_sum`` applies the sigmoid HERE because exp is
    implementation-defined — XLA's logistic and libm's expf disagree in
    the last ulp on ~4% of inputs, so neither engine may bake it in.
    """
    m = np.asarray(margin, dtype=np.float32)
    if forest.aggregation == "mean":
        return m / np.float32(forest.n_trees)
    if forest.aggregation == "logit_sum":
        z = m + np.float32(forest.base_score)
        return (np.float32(1.0) / (np.float32(1.0) + np.exp(-z))).astype(np.float32)
    raise ValueError(f"unknown aggregation {forest.aggregation!r}")


def predict_score(forest: FlatForest, x: jnp.ndarray) -> jnp.ndarray:
    """TREE_SCORE in [0,1] for a (N, F) feature matrix (jit-safe).

    Device-finalized convenience wrapper over :func:`predict_margin` —
    accelerator callers keep everything on device. The engine-parity
    paths (pipelines/filter_variants) instead fetch the margin and
    finalize on the host via :func:`finalize_margin`, because the device
    sigmoid's exp is not bit-portable.
    """
    return _device_finalize(predict_margin(forest, x), forest.aggregation,
                            forest.n_trees, forest.base_score)


@dataclass
class GemmForest:
    """MXU-friendly forest encoding (Hummingbird-style GEMM strategy).

    Tree traversal recast as matmuls so inference rides the systolic array
    instead of XLA's (slow on TPU) dynamic gathers:

      XF    = X @ A          (N,F)@(F,I) one-hot feature pick per internal node
      D     = XF <= thr      {0,1} decisions
      match = D @ M2 + c     (N,I)@(I,L); M2 = 2*B - P with B[i,l]=1 iff leaf
                             l sits in i's LEFT subtree, P[i,l]=1 iff i is on
                             l's path; c[l] = #right-turns on l's path
      leaf  = (match == path_len)   — exactly one leaf matches
      score = leaf @ value

    All matmul operands are small exact integers (|M2|<=1, path sums <=
    depth), so the routing matmuls are bit-exact even in bf16; the feature
    pick runs at HIGHEST precision to keep threshold compares faithful.
    """

    a: np.ndarray  # f32 (T, F, I) one-hot feature selectors
    thr: np.ndarray  # f32 (T, I)
    m2: np.ndarray  # f32 (T, I, L) = 2B - P
    c: np.ndarray  # f32 (T, L) right-turn counts
    plen: np.ndarray  # f32 (T, L); -1 for padded leaves
    value: np.ndarray  # f32 (T, L)
    aggregation: str
    base_score: float
    # missing routing: None when the source forest has no default_left bits
    # (no NaN machinery in the compiled program); else f32 (T, I) 0/1
    dleft: np.ndarray | None = None

    @property
    def n_leaves(self) -> int:
        return self.m2.shape[2]


def to_gemm(forest: FlatForest, n_features: int | None = None) -> GemmForest:
    """Rewrite a FlatForest into path-matrix (GEMM) form (host-side, once)."""
    t = forest.n_trees
    n_features = int(n_features if n_features is not None else max(int(forest.feature.max()) + 1, 1))
    per_tree = []
    max_i, max_l = 1, 1
    for ti in range(t):
        feat, left, right = forest.feature[ti], forest.left[ti], forest.right[ti]
        internals: list[int] = []
        leaves: list[int] = []
        paths: list[list[tuple[int, bool]]] = []
        stack: list[tuple[int, list[tuple[int, bool]]]] = [(0, [])]
        while stack:
            node, path = stack.pop()
            if feat[node] == LEAF:
                leaves.append(node)
                paths.append(path)
            else:
                k = len(internals)
                internals.append(node)
                stack.append((int(right[node]), path + [(k, False)]))
                stack.append((int(left[node]), path + [(k, True)]))
        per_tree.append((internals, leaves, paths))
        max_i = max(max_i, len(internals))
        max_l = max(max_l, len(leaves))
    a = np.zeros((t, n_features, max_i), dtype=np.float32)
    thr = np.zeros((t, max_i), dtype=np.float32)
    m2 = np.zeros((t, max_i, max_l), dtype=np.float32)
    c = np.zeros((t, max_l), dtype=np.float32)
    plen = np.full((t, max_l), -1.0, dtype=np.float32)  # -1: padded leaf never matches
    value = np.zeros((t, max_l), dtype=np.float32)
    dleft = None if forest.default_left is None else np.zeros((t, max_i), dtype=np.float32)
    for ti, (internals, leaves, paths) in enumerate(per_tree):
        for k, node in enumerate(internals):
            a[ti, forest.feature[ti, node], k] = 1.0
            thr[ti, k] = forest.threshold[ti, node]
            if dleft is not None:
                dleft[ti, k] = float(forest.default_left[ti, node])
        for j, (node, path) in enumerate(zip(leaves, paths)):
            value[ti, j] = forest.value[ti, node]
            plen[ti, j] = len(path)
            for k, went_left in path:
                m2[ti, k, j] = 1.0 if went_left else -1.0  # 2B-P: left=+1, right=-1
                if not went_left:
                    c[ti, j] += 1.0
    return GemmForest(a, thr, m2, c, plen, value, forest.aggregation, forest.base_score,
                      dleft=dleft)


# beyond this many leaves per tree the (N,I)@(I,L) routing matmul costs more
# than the gather walk saves; the AUTO strategy falls back to the gather
# traversal (an explicit VCTPU_FOREST_STRATEGY override is honored anyway)
GEMM_MAX_LEAVES = 512


def _device_finalize(margin: jnp.ndarray, aggregation: str, n_trees: int,
                     base_score: float) -> jnp.ndarray:
    """Margin -> score ON DEVICE (accelerator convenience; NOT bit-portable
    for logit_sum — engine-parity callers use the host finalize_margin)."""
    if aggregation == "mean":
        return margin / n_trees
    if aggregation == "logit_sum":
        return jax.nn.sigmoid(margin + base_score)
    raise ValueError(f"unknown aggregation {aggregation!r}")


def predict_margin_gemm(gf: GemmForest, x: jnp.ndarray) -> jnp.ndarray:
    """Raw canonical-order margin via the matmul formulation (jit-safe).

    Scans over trees so peak memory is O(N * (I+L)) rather than
    O(T * N * L): each step is two (N,·)@(·,·) matmuls that tile cleanly
    onto the systolic array. The scan carry accumulates per-tree leaf
    values in tree order — the same loop-carried (non-reassociable)
    sequence :func:`sequential_tree_sum` runs — so margins are
    bit-identical to the gather walk and the native C++ engine.
    """
    missing = gf.dleft is not None
    tables = (
        jnp.asarray(gf.a),
        jnp.asarray(gf.thr),
        jnp.asarray(gf.m2),
        jnp.asarray(gf.c),
        jnp.asarray(gf.plen),
        jnp.asarray(gf.value),
    ) + ((jnp.asarray(gf.dleft),) if missing else ())
    if missing:
        # NaN would poison every xf entry through the feature-pick matmul;
        # pick from a scrubbed copy and matmul the NaN mask through the
        # same selector to know, per node, whether its feature was missing
        x_miss = jnp.isnan(x).astype(jnp.float32)
        x = jnp.nan_to_num(x, nan=0.0)

    def per_tree(acc, tree):
        a, thr, m2, c, plen, value = tree[:6]
        # one-hot feature pick must preserve f32 values exactly: default
        # matmul precision rounds operands to bf16
        xf = jnp.dot(x, a, precision=jax.lax.Precision.HIGHEST)  # (N,I)
        d = (xf <= thr[None, :]).astype(jnp.float32)
        if missing:  # 0/1 mask matmul is exact even in bf16
            mf = jnp.dot(x_miss, a)  # (N,I) 1 where the node's feature is NaN
            d = jnp.where(mf > 0.5, tree[6][None, :], d)
        # routing matmul: operands are small exact integers — bf16-safe
        match = jnp.dot(d, m2) + c[None, :]  # (N,L)
        onehot = (match == plen[None, :]).astype(jnp.float32)
        s = jnp.dot(onehot, value, precision=jax.lax.Precision.HIGHEST)  # (N,)
        return acc + s, None

    total, _ = jax.lax.scan(per_tree, jnp.zeros(x.shape[0], dtype=jnp.float32), tables)
    return total


def predict_score_gemm(gf: GemmForest, x: jnp.ndarray) -> jnp.ndarray:
    """TREE_SCORE via the matmul formulation (device-finalized wrapper)."""
    return _device_finalize(predict_margin_gemm(gf, x), gf.aggregation,
                            gf.m2.shape[0], gf.base_score)


# --------------------------------------------------------------------------
# wide-contraction encoding: all trees per MXU pass
# --------------------------------------------------------------------------

#: default N-chunk of the wide driver (VCTPU_WIDE_CHUNK overrides): bounds
#: the decision tensor at O(chunk * T*I) and the routing intermediate at
#: O(chunk * G*L), so 5M-variant scoring never materializes (N, T*L)
WIDE_CHUNK = 1 << 14
WIDE_CHUNK_ENV = "VCTPU_WIDE_CHUNK"
#: tree-group blocking knob (G trees per routing block; VCTPU_WIDE_BLOCK)
WIDE_BLOCK_ENV = "VCTPU_WIDE_BLOCK"


def _int_env(name: str) -> int | None:
    """Positive-integer env knob, or None when unset. A malformed value is
    a configuration error (EngineError, CLI exit 2) like a bad
    VCTPU_ENGINE/VCTPU_FOREST_STRATEGY — never a mid-run ValueError
    traceback from inside a jit trace. Parsing lives in the typed knob
    registry (:mod:`variantcalling_tpu.knobs`)."""
    from variantcalling_tpu import knobs

    return knobs.get_int(name)


def default_tree_block(n_internal: int) -> int:
    """G such that the routing contraction dim G*I fills one 128-lane MXU
    tile: the block-diagonal operand wastes O(G^2) dense FLOPs, so G grows
    only until the contraction lanes are full (docs/perf_notes.md roofline:
    G=4 for I=31 -> K=124, 97% lane fill vs 24% for the per-tree scan)."""
    return max(1, 128 // max(n_internal, 1))


def resolved_tree_block(n_internal: int, n_trees: int,
                        tree_block: int | None = None) -> int:
    """The G :func:`to_wide` will actually pack with (arg beats the
    VCTPU_WIDE_BLOCK env beats the MXU-fill default; clamped to T) —
    shared with bench's FLOP attribution so MFU math cannot drift from
    the packing."""
    if tree_block is None:
        tree_block = _int_env(WIDE_BLOCK_ENV) or default_tree_block(n_internal)
    return max(1, min(int(tree_block), n_trees))


@dataclass
class WideGemmForest:
    """Block-packed wide-contraction forest (all trees per MXU pass).

    The per-tree scan (``predict_margin_gemm``) issues (N,F)@(F,I) and
    (N,I)@(I,L) matmuls whose contraction dims fill 9-24% of the 128-lane
    MXU. This encoding packs trees side by side so one pass computes every
    tree: the feature pick becomes (N,F)@(F,Tp*I) (K stays F but the
    output tile is Tp*I lanes wide), and routing becomes a BLOCK-DIAGONAL
    (N,G*I)@(G*I,G*L) contraction over groups of G trees. Trees are padded
    to Tp = ceil(T/G)*G with never-matching dummies (plen=-1, value=0);
    padding never enters the margin reduction (sliced off before
    :func:`sequential_tree_sum`), so the canonical tree order is exactly
    the real trees'.
    """

    a: np.ndarray  # f32 (B, F, G*I) per-block feature selectors
    thr: np.ndarray  # f32 (B, G*I)
    m2: np.ndarray  # f32 (B, G*I, G*L) block-diagonal routing
    c: np.ndarray  # f32 (B, G*L)
    plen: np.ndarray  # f32 (B, G*L); -1 for padded leaves AND padded trees
    value: np.ndarray  # f32 (B, G, L)
    dleft: np.ndarray | None  # f32 (B, G*I) or None
    n_trees: int  # real T — the slice fed to sequential_tree_sum
    tree_block: int  # G
    aggregation: str
    base_score: float

    @property
    def n_blocks(self) -> int:
        return self.m2.shape[0]


def to_wide(gf: GemmForest, tree_block: int | None = None) -> WideGemmForest:
    """Pack a GemmForest into block-diagonal wide operands (host, once)."""
    t, f, i = gf.a.shape
    l = gf.m2.shape[2]
    g = resolved_tree_block(i, t, tree_block)
    b = -(-t // g)
    tp = b * g

    def pad_trees(arr, fill=0.0):
        if tp == t:
            return arr
        width = [(0, tp - t)] + [(0, 0)] * (arr.ndim - 1)
        return np.pad(arr, width, constant_values=fill)

    a_p = pad_trees(gf.a)  # (Tp, F, I)
    thr_p = pad_trees(gf.thr)
    m2_p = pad_trees(gf.m2).reshape(b, g, i, l)
    c_p = pad_trees(gf.c)
    plen_p = pad_trees(gf.plen, fill=-1.0)  # padded trees: no leaf matches
    value_p = pad_trees(gf.value)
    a_w = np.ascontiguousarray(
        a_p.reshape(b, g, f, i).transpose(0, 2, 1, 3).reshape(b, f, g * i))
    m2_w = np.zeros((b, g * i, g * l), dtype=np.float32)
    for gi in range(g):
        m2_w[:, gi * i:(gi + 1) * i, gi * l:(gi + 1) * l] = m2_p[:, gi]
    dleft_w = None if gf.dleft is None else \
        pad_trees(gf.dleft).reshape(b, g * i)
    return WideGemmForest(
        a=a_w, thr=thr_p.reshape(b, g * i), m2=m2_w,
        c=c_p.reshape(b, g * l), plen=plen_p.reshape(b, g * l),
        value=value_p.reshape(b, g, l), dleft=dleft_w,
        n_trees=t, tree_block=g,
        aggregation=gf.aggregation, base_score=gf.base_score)


def wide_chunk() -> int:
    return _int_env(WIDE_CHUNK_ENV) or WIDE_CHUNK


def predict_pertree_margin_wide(wf: WideGemmForest, x: jnp.ndarray) -> jnp.ndarray:
    """(N, T) per-tree leaf margins via the wide-contraction formulation
    for ONE chunk (no internal N-chunking — see predict_margin_wide).

    Exactness: the feature pick runs at HIGHEST precision (threshold
    compares must see exact f32 values); the routing operands are exact
    small integers (bf16-safe); the leaf pick multiplies a 0/1 one-hot by
    the f32 leaf values and reduces over leaves — all-but-one terms are
    exact +0.0, so the per-tree margin is the exact leaf value regardless
    of reduction order. Bit-identical per-tree margins => bit-identical
    canonical-order sums.
    """
    missing = wf.dleft is not None
    n = x.shape[0]
    b = wf.n_blocks
    g = wf.tree_block
    gi = wf.thr.shape[1]
    a = jnp.asarray(wf.a).transpose(1, 0, 2).reshape(wf.a.shape[1], b * gi)
    thr = jnp.asarray(wf.thr).reshape(b * gi)
    if missing:
        x_miss = jnp.isnan(x).astype(jnp.float32)
        x = jnp.nan_to_num(x, nan=0.0)
    # ONE wide feature pick for every tree: (N,F)@(F,Tp*I)
    xf = jnp.dot(x, a, precision=jax.lax.Precision.HIGHEST)
    d = (xf <= thr[None, :]).astype(jnp.float32)
    if missing:
        mf = jnp.dot(x_miss, a)  # exact 0/1 matmul
        d = jnp.where(mf > 0.5, jnp.asarray(wf.dleft).reshape(b * gi)[None, :], d)
    d_blocks = d.reshape(n, b, gi).transpose(1, 0, 2)  # (B, N, G*I)

    def per_block(_, blk):
        db, m2b, cb, plenb, valb = blk
        # block-diagonal routing: (N,G*I)@(G*I,G*L), exact small ints
        match = jnp.dot(db, m2b) + cb[None, :]
        onehot = (match == plenb[None, :]).astype(jnp.float32)  # (N, G*L)
        # per-tree leaf pick: one exact f32 survives per (variant, tree)
        # (explicit leaf dim — reshape(-1) cannot infer it when n == 0)
        margins = jnp.einsum("ngl,gl->ng",
                             onehot.reshape(n, g, valb.shape[1]), valb,
                             precision=jax.lax.Precision.HIGHEST)
        return None, margins

    xs = (d_blocks, jnp.asarray(wf.m2), jnp.asarray(wf.c),
          jnp.asarray(wf.plen), jnp.asarray(wf.value))
    _, per_tree = jax.lax.scan(per_block, None, xs)  # (B, N, G)
    return per_tree.transpose(1, 0, 2).reshape(n, b * g)[:, :wf.n_trees]


def predict_margin_wide(wf: WideGemmForest, x: jnp.ndarray) -> jnp.ndarray:
    """Raw canonical-order margin via wide contractions (jit-safe).

    N-chunked driver: chunks of :func:`wide_chunk` variants run through
    ``lax.map`` so peak memory stays O(chunk * T*I) however large N is
    (the pipeline's outer 256k chunks would otherwise materialize a
    ~1.2 GB decision tensor at T=40). Rows are independent, so chunking
    cannot change any variant's bits.
    """
    n = x.shape[0]
    chunk = wide_chunk()

    def chunk_margin(xc):
        return sequential_tree_sum(predict_pertree_margin_wide(wf, xc))

    if n <= chunk:
        return chunk_margin(x)
    pad = (-n) % chunk
    xp = jnp.pad(x, ((0, pad), (0, 0))) if pad else x
    out = jax.lax.map(chunk_margin, xp.reshape(-1, chunk, x.shape[1]))
    return out.reshape(-1)[:n]


def predict_score_wide(wf: WideGemmForest, x: jnp.ndarray) -> jnp.ndarray:
    """TREE_SCORE via wide contractions (device-finalized wrapper)."""
    return _device_finalize(predict_margin_wide(wf, x), wf.aggregation,
                            wf.n_trees, wf.base_score)


#: Strategy chosen by the most recent make_predictor/make_margin_predictor
#: call — bench logs it so a silent pallas->wide (or wide->gather) fallback
#: is visible in the captured perf evidence instead of invisibly changing
#: what was measured.
last_strategy: str = "none"

#: explicit strategy override: {auto,gather,gemm,wide,pallas}
FOREST_STRATEGY_ENV = "VCTPU_FOREST_STRATEGY"
FOREST_STRATEGIES = ("auto", "gather", "gemm", "wide", "pallas")
#: the VCF header key the filter pipeline records the resolved strategy
#: under (next to ##vctpu_engine=; part of the chunk-journal resume identity)
STRATEGY_HEADER_KEY = "vctpu_forest_strategy"


def requested_strategy() -> str:
    """The env-requested strategy; raises EngineError on a bad value (the
    same fail-loudly style as a bad VCTPU_ENGINE — parse and validation
    live in the typed knob registry)."""
    from variantcalling_tpu import knobs

    return knobs.get_str(FOREST_STRATEGY_ENV)


def validate_strategy_env() -> None:
    """Up-front validation of EVERY strategy-related env knob (strategy
    name, wide chunk, wide block) — FilterContext calls this once per run
    so a malformed value exits 2 with a clear message before any scoring,
    on every engine, instead of surfacing mid-run from inside a jit
    trace."""
    requested_strategy()
    _int_env(WIDE_CHUNK_ENV)
    _int_env(WIDE_BLOCK_ENV)


def _backend() -> str:
    try:
        return jax.default_backend()
    except Exception as e:  # backend init failure must not break program construction
        from variantcalling_tpu.utils import degrade

        degrade.record("forest.backend_probe", e, fallback='backend="cpu"')
        return "cpu"


def max_tree_leaves(forest: FlatForest) -> int:
    """Reachable leaves of the biggest tree, WITHOUT the O(T * nodes)
    Python traversal :func:`to_gemm` performs: every stored internal node
    is reachable and the trees are full binary (sklearn/xgboost/boosting
    ingest all guarantee both), so leaves = internal nodes + 1 — padding
    rows are feature=LEAF and do not count as internal. Matches
    ``to_gemm(forest).n_leaves`` (asserted in tests) at vectorized cost."""
    return int((forest.feature != LEAF).sum(axis=1).max()) + 1


def resolve_strategy(forest: FlatForest, n_features: int | None = None,
                     backend: str | None = None) -> str:
    """The concrete strategy a run will score with (never ``auto``) —
    resolved ONCE per run by the filter pipeline, recorded in the output
    header and the chunk-journal resume identity, and then PINNED: the
    predictor build honors it or fails loudly, so the recorded name can
    never silently diverge from the program that scored.

    Auto policy: CPU keeps the gather walk (the pipeline routes CPU
    single-device scoring through the native C++ engine before reaching
    here; this is the jit engine's CPU program). Accelerators take the
    wide-contraction GEMM; TPUs take the pallas wide-block kernel when
    enabled (VCTPU_PALLAS=0 opts out) and the forest has no missing-value
    routing (the kernel's known gap). Trees beyond GEMM_MAX_LEAVES fall
    back to the gather walk everywhere.
    """
    from variantcalling_tpu import knobs, obs

    req = requested_strategy()
    if req != "auto":
        resolved, why = req, "explicitly requested"
    else:
        backend = backend or _backend()
        if backend == "cpu":
            resolved, why = "gather", "auto: cpu backend keeps the gather walk"
        elif max_tree_leaves(forest) > GEMM_MAX_LEAVES:
            resolved, why = "gather", "auto: tree leaves exceed GEMM_MAX_LEAVES"
        elif backend == "tpu" and knobs.get_bool("VCTPU_PALLAS") \
                and forest.default_left is None:
            resolved, why = "pallas", "auto: tpu backend, pallas enabled"
        else:
            resolved, why = "wide", f"auto: {backend} backend wide-contraction"
    if obs.active():
        obs.event("resolve", "forest_strategy", value=resolved,
                  requested=req, reason=why)
    return resolved


def _build_margin_program(strategy: str, forest: FlatForest,
                          n_features: int | None):
    """fn(x) -> canonical-order margin for one concrete strategy.

    Raises on anything the strategy cannot serve (pallas lowering gaps,
    bad env values) — the CALLER decides whether that is a loud failure
    (explicitly requested strategy) or an auto fallback.
    """
    if strategy == "gather":
        return lambda x: predict_margin(forest, x)
    gf = to_gemm(forest, n_features)
    if strategy == "gemm":
        return lambda x: predict_margin_gemm(gf, x)
    if strategy == "wide":
        wf = to_wide(gf)
        return lambda x: predict_margin_wide(wf, x)
    if strategy == "pallas":
        from variantcalling_tpu.models.forest_pallas import \
            make_wide_pallas_margin_predictor

        fn = make_wide_pallas_margin_predictor(gf)
        # lowering failures only surface at the first call — warm up HERE
        # so a gap is attributable to construction, not to a random caller
        n_feat = gf.a.shape[1]
        jax.block_until_ready(jax.jit(fn)(jnp.zeros((1, n_feat), jnp.float32)))
        return fn
    raise ValueError(f"unknown forest strategy {strategy!r}")


#: auto-mode fallback order after the resolved strategy fails to build
_AUTO_FALLBACK = ("wide", "gemm", "gather")


def make_margin_predictor(forest: FlatForest, n_features: int | None = None,
                          strategy: str | None = None):
    """jittable fn(x) -> canonical-order margin, by strategy.

    ``strategy=None`` reads ``VCTPU_FOREST_STRATEGY`` (default ``auto``).
    An EXPLICITLY requested strategy (argument or env, not ``auto``) that
    cannot build FAILS LOUDLY with EngineError (exit-2 style at the CLI) —
    the PR-2 contract: a pinned configuration is honored or the run dies,
    never silently degraded (the old ``make_predictor`` swallowed pallas
    lowering failures with a bare except). Auto mode keeps the documented
    fallback chain (pallas -> wide -> gemm -> gather), each hop recorded
    in :data:`last_strategy`.

    Every strategy returns the SAME bits: bit-exact per-tree leaf margins
    reduced in canonical tree order (:func:`sequential_tree_sum` /
    the scan carry), finalized by the caller through the one shared
    :func:`finalize_margin`.
    """
    global last_strategy
    from variantcalling_tpu.engine import EngineError

    req = strategy if strategy is not None else requested_strategy()
    explicit = req != "auto"
    if explicit and req not in FOREST_STRATEGIES:
        raise EngineError(
            f"forest strategy {req!r} is not one of "
            f"{'/'.join(FOREST_STRATEGIES[1:])}")
    resolved = req if explicit else resolve_strategy(forest, n_features)
    try:
        fn = _build_margin_program(resolved, forest, n_features)
    except Exception as e:  # noqa: BLE001 — fate decided by explicitness
        if explicit:
            raise EngineError(
                f"forest strategy '{resolved}' was explicitly requested "
                f"({FOREST_STRATEGY_ENV} or a pinned run configuration) but "
                f"cannot serve this forest/backend: {type(e).__name__}: {e}. "
                "Refusing to silently fall back — rerun with "
                f"{FOREST_STRATEGY_ENV}=auto to opt into fallback, or "
                "VCTPU_PALLAS=0 if the pallas kernel cannot serve this "
                "forest (the filter pipeline pins auto's resolution, so "
                "re-running auto repeats this choice). "
                "See docs/models.md.") from e
        from variantcalling_tpu.utils import degrade

        degrade.record("forest.auto_fallback", e,
                       fallback=f"auto-resolved strategy {resolved!r} cannot "
                       "build; walking the fallback chain", warn=True)
        fn = None
        for fb in _AUTO_FALLBACK:
            if fb == resolved:
                continue
            try:
                fn = _build_margin_program(fb, forest, n_features)
                resolved = fb
                break
            except Exception as fb_err:  # noqa: BLE001 — keep walking the chain
                from variantcalling_tpu.utils import degrade

                degrade.record("forest.auto_fallback", fb_err,
                               fallback=f"strategy {fb!r} also failed; "
                               "trying next in chain", warn=True)
                continue
        if fn is None:
            raise
    last_strategy = resolved  # vctpu-lint: disable=VCT010 — run-scoped diagnostic; GIL-atomic store, the strategy is pinned per run so every writer agrees
    return fn


def make_predictor(forest: FlatForest, n_features: int | None = None,
                   strategy: str | None = None):
    """Device-finalized fn(x) -> scores (accelerator/bench convenience):
    the strategy-resolved margin program plus the on-device finalize.
    Engine-parity callers (the filter pipeline) use
    :func:`make_margin_predictor` + host :func:`finalize_margin` instead,
    because the device sigmoid's exp is not bit-portable. Records the
    choice in :data:`last_strategy`."""
    fn = make_margin_predictor(forest, n_features, strategy=strategy)
    agg, base = forest.aggregation, forest.base_score
    n_trees = forest.n_trees
    return lambda x: _device_finalize(fn(x), agg, n_trees, base)


def native_host_predictor(forest: FlatForest, strict: bool = False):
    """CPU fast path: the exact predict_score walk in C++ as a plain HOST
    function (numpy in, numpy out) — ~5x XLA:CPU's fused-gather lowering
    on one core. Callers split their program at the feature matrix and
    run this outside jit (a pure_callback inside the async chunk pipeline
    can deadlock XLA:CPU's single-threaded callback executor). Returns
    None when the native library is unavailable or the aggregation is
    unknown; use only on the CPU backend (accelerators keep GEMM/pallas).

    ``strict=True`` (the pinned-native engine paths): a mid-run native
    failure RAISES instead of silently computing the margin via XLA —
    an output stamped ``##vctpu_engine=native`` must never contain
    jit-scored rows (engine contract, docs/robustness.md)."""
    from variantcalling_tpu import native

    if not native.available() or forest.aggregation not in ("mean", "logit_sum"):
        return None
    feat = np.ascontiguousarray(forest.feature, dtype=np.int32)
    thr = np.ascontiguousarray(forest.threshold, dtype=np.float32)
    left = np.ascontiguousarray(forest.left, dtype=np.int32)
    right = np.ascontiguousarray(forest.right, dtype=np.int32)
    value = np.ascontiguousarray(forest.value, dtype=np.float32)
    dl = None if forest.default_left is None else \
        np.ascontiguousarray(forest.default_left, dtype=np.uint8)
    depth = forest.max_depth

    def fn(x: np.ndarray) -> np.ndarray:
        # raw canonical-order sums from the C++ walk; finalization happens
        # in the SHARED host code so the bits match the jit engine exactly
        margin = native.forest_predict(np.asarray(x), feat, thr, left, right,
                                       value, dl, depth, "sum", 0.0)
        if margin is None:
            if strict:
                from variantcalling_tpu.engine import EngineError

                raise EngineError(
                    "the native forest walk failed mid-run with the engine "
                    "pinned to native — refusing to silently score on the "
                    "jit walk. See docs/robustness.md.")
            # opportunistic callers: jnp walk fallback (bit-identical, the
            # canonical-order margin is engine-independent by construction)
            margin = np.asarray(predict_margin(forest, jnp.asarray(x)))
        return finalize_margin(margin, forest)

    return fn


def native_cols_predictor(forest: FlatForest):
    """CPU fast path over raw feature COLUMNS: the native engine tiles the
    column->matrix transpose L2-resident and walks each tile immediately,
    so the (n, f) float32 matrix never materializes (at 5M x 19 that is
    ~760 MB of skipped DRAM traffic vs build_matrix + the row walk).
    Bit-identical scores to :func:`native_host_predictor`. Returns None
    when unavailable; fn returns None when a column dtype is unsupported
    (caller falls back to the two-step path)."""
    from variantcalling_tpu import native

    if not native.available() or forest.aggregation not in ("mean", "logit_sum"):
        return None
    feat = np.ascontiguousarray(forest.feature, dtype=np.int32)
    thr = np.ascontiguousarray(forest.threshold, dtype=np.float32)
    left = np.ascontiguousarray(forest.left, dtype=np.int32)
    right = np.ascontiguousarray(forest.right, dtype=np.int32)
    value = np.ascontiguousarray(forest.value, dtype=np.float32)
    dl = None if forest.default_left is None else \
        np.ascontiguousarray(forest.default_left, dtype=np.uint8)
    depth = forest.max_depth

    def fn(cols: list[np.ndarray]) -> np.ndarray | None:
        margin = native.matrix_forest_predict(cols, feat, thr, left, right, value,
                                              dl, depth, "sum", 0.0)
        if margin is None:
            return None
        return finalize_margin(margin, forest)

    return fn


def from_sklearn(clf, feature_names: list[str] | None = None, pass_threshold: float = 0.5) -> FlatForest:
    """Flatten a fitted sklearn RandomForestClassifier/DecisionTree ensemble.

    Faithful to sklearn semantics: split is ``x[f] <= threshold`` goes left
    (sklearn uses <=); leaf value = class-1 fraction of training samples in
    the leaf; prediction = mean over trees (predict_proba).
    """
    raw = getattr(clf, "estimators_", None)
    if raw is None:
        estimators = [clf]
    elif isinstance(raw, np.ndarray):
        # GradientBoosting stores an (n_stages, n_classes) ndarray of
        # regressor trees -> boosted-margin aggregation, not mean-proba
        if raw.ndim == 2 and raw.shape[1] != 1:
            raise ValueError("only binary-class boosted ensembles are supported")
        return _from_sklearn_gbt(clf, raw.ravel().tolist(), feature_names, pass_threshold)
    else:
        estimators = list(raw)
    n_nodes = [e.tree_.node_count for e in estimators]
    m = max(n_nodes)
    t = len(estimators)
    feature = np.full((t, m), LEAF, dtype=np.int32)
    threshold = np.zeros((t, m), dtype=np.float32)
    left = np.zeros((t, m), dtype=np.int32)
    right = np.zeros((t, m), dtype=np.int32)
    value = np.zeros((t, m), dtype=np.float32)
    max_depth = 1
    for ti, est in enumerate(estimators):
        tr = est.tree_
        nc = tr.node_count
        f = tr.feature.astype(np.int32)
        is_leaf = tr.children_left == -1
        feature[ti, :nc] = np.where(is_leaf, LEAF, f)
        # sklearn compares float32-cast x against float64 thresholds; storing
        # the largest f32 <= threshold keeps `x <= thr` decisions bit-identical
        thr64 = tr.threshold
        thr32 = thr64.astype(np.float32)
        too_big = thr32.astype(np.float64) > thr64
        thr32[too_big] = np.nextafter(thr32[too_big], np.float32(-np.inf))
        threshold[ti, :nc] = thr32
        node_ids = np.arange(nc, dtype=np.int32)
        left[ti, :nc] = np.where(is_leaf, node_ids, tr.children_left)
        right[ti, :nc] = np.where(is_leaf, node_ids, tr.children_right)
        counts = tr.value[:, 0, :]  # (nc, n_classes) — class sample fractions
        if counts.shape[1] == 2:
            denom = counts.sum(axis=1)
            value[ti, :nc] = np.where(denom > 0, counts[:, 1] / np.maximum(denom, 1e-12), 0.0)
        else:
            # degenerate single-class fit: every leaf predicts that class
            classes = getattr(est, "classes_", getattr(clf, "classes_", np.array([1])))
            value[ti, :nc] = 1.0 if classes[0] == 1 else 0.0
        max_depth = max(max_depth, int(tr.max_depth))
    return FlatForest(
        feature=feature,
        threshold=threshold,
        left=left,
        right=right,
        value=value,
        max_depth=max_depth,
        aggregation="mean",
        feature_names=feature_names or [],
        pass_threshold=pass_threshold,
    )


def _from_sklearn_gbt(clf, trees: list, feature_names: list[str] | None, pass_threshold: float) -> FlatForest:
    """Flatten a fitted binary GradientBoostingClassifier.

    score = sigmoid(init_log_odds + lr * sum(tree margins)) — matches
    sklearn's staged decision function for the log-loss binary case.
    """
    lr = float(getattr(clf, "learning_rate", 1.0))
    base = 0.0
    init = getattr(clf, "init_", None)
    if init is not None and hasattr(init, "class_prior_"):
        p1 = float(np.clip(init.class_prior_[-1], 1e-12, 1 - 1e-12))
        base = float(np.log(p1 / (1 - p1)))
    m = max(t.tree_.node_count for t in trees)
    t_n = len(trees)
    feature = np.full((t_n, m), LEAF, dtype=np.int32)
    threshold = np.zeros((t_n, m), dtype=np.float32)
    left = np.zeros((t_n, m), dtype=np.int32)
    right = np.zeros((t_n, m), dtype=np.int32)
    value = np.zeros((t_n, m), dtype=np.float32)
    max_depth = 1
    for ti, est in enumerate(trees):
        tr = est.tree_
        nc = tr.node_count
        is_leaf = tr.children_left == -1
        feature[ti, :nc] = np.where(is_leaf, LEAF, tr.feature.astype(np.int32))
        thr64 = tr.threshold
        thr32 = thr64.astype(np.float32)
        too_big = thr32.astype(np.float64) > thr64
        thr32[too_big] = np.nextafter(thr32[too_big], np.float32(-np.inf))
        threshold[ti, :nc] = thr32
        node_ids = np.arange(nc, dtype=np.int32)
        left[ti, :nc] = np.where(is_leaf, node_ids, tr.children_left)
        right[ti, :nc] = np.where(is_leaf, node_ids, tr.children_right)
        value[ti, :nc] = lr * tr.value[:, 0, 0]
        max_depth = max(max_depth, int(tr.max_depth))
    return FlatForest(
        feature=feature,
        threshold=threshold,
        left=left,
        right=right,
        value=value,
        max_depth=max_depth,
        aggregation="logit_sum",
        base_score=base,
        feature_names=feature_names or [],
        pass_threshold=pass_threshold,
    )


def with_feature_order(forest: FlatForest, feature_names: list[str]) -> FlatForest:
    """Remap node feature indices to a new feature-column order."""
    if not forest.feature_names or forest.feature_names == feature_names:
        return forest
    mapping = np.asarray([feature_names.index(f) for f in forest.feature_names], dtype=np.int32)
    new_feat = np.where(forest.feature == LEAF, LEAF, mapping[np.maximum(forest.feature, 0)])
    return replace(forest, feature=new_feat.astype(np.int32), feature_names=list(feature_names))
