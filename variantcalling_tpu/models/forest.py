"""Decision-forest inference as vmap'd node-gather traversal on TPU.

The reference's hot loop is sklearn RandomForest / xgboost ``predict_proba``
over ~5M variants on CPU (docs/howto-callset-filter.md:63,114; BASELINE
north_star). Here a trained forest is flattened into dense per-tree node
arrays and traversal is ``max_depth`` rounds of batched gathers — fully
vectorized over (variants × trees), jit/pjit-safe, and shardable along the
variants axis. Works for both class-probability forests (RF: mean of leaf
probabilities) and boosted margins (GBT: sum + sigmoid).
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace

import numpy as np

import jax
import jax.numpy as jnp

LEAF = -1


@dataclass
class FlatForest:
    """Dense forest: (n_trees, max_nodes) arrays; leaves self-loop with feature=LEAF."""

    feature: np.ndarray  # int32 (T, M); LEAF for leaf nodes
    threshold: np.ndarray  # float32 (T, M)
    left: np.ndarray  # int32 (T, M)
    right: np.ndarray  # int32 (T, M)
    value: np.ndarray  # float32 (T, M): leaf payload (class-1 prob or margin)
    max_depth: int
    aggregation: str = "mean"  # "mean" (RF proba) | "logit_sum" (GBT margin)
    base_score: float = 0.0  # added before sigmoid for logit_sum
    feature_names: list[str] = field(default_factory=list)
    pass_threshold: float = 0.5  # TREE_SCORE >= this -> PASS
    # xgboost-style missing-value routing: NaN features take the node's
    # default branch. None = no missing routing (NaN routes right, since
    # all NaN comparisons are false) — sklearn/boosting models never see
    # NaN (host columns are nan_to_num'd), so the hot path stays free of
    # the extra gather.
    default_left: np.ndarray | None = None  # bool (T, M) or None

    @property
    def n_trees(self) -> int:
        return self.feature.shape[0]

    def astuple(self):
        return (
            jnp.asarray(self.feature),
            jnp.asarray(self.threshold),
            jnp.asarray(self.left),
            jnp.asarray(self.right),
            jnp.asarray(self.value),
        )


def predict_margin(forest: FlatForest, x: jnp.ndarray) -> jnp.ndarray:
    """Raw per-variant leaf-value SUM in canonical tree order (jit-safe).

    Traversal: ``max_depth`` rounds of gathers; each round every (variant,
    tree) pair advances one level (leaves self-loop), so control flow is
    static and XLA lowers the whole forest to fused gathers — no
    per-variant Python, no host sync.

    The accumulation is a SEQUENTIAL fori_loop over trees (t=0,1,...,T-1)
    rather than ``jnp.sum``: XLA's reduce reassociates f32 sums into
    SIMD-lane partials whose grouping varies with backend and device
    count, which made jit scores differ from the native C++ walk (and
    from themselves across mesh shapes) by 1 ulp — the round-5 multihost
    byte-parity flake. A loop-carried dependency cannot be reassociated,
    and the native walk accumulates in the same order
    (``native/src/vctpu_gbt.cc`` forest_walk_tile), so the two engines'
    sums are bit-identical (tests/unit/test_engine_contract.py).
    """
    feat, thr, left, right, value = forest.astuple()
    dl = None if forest.default_left is None else jnp.asarray(forest.default_left)
    n = x.shape[0]
    t = feat.shape[0]
    tree_ids = jnp.arange(t)[None, :]  # (1, T)

    def body(_, idx):
        f = feat[tree_ids, idx]  # (N, T)
        th = thr[tree_ids, idx]
        xv = jnp.take_along_axis(x, jnp.maximum(f, 0), axis=1)  # (N, T)
        go_left = xv <= th
        if dl is not None:  # missing (NaN) takes the node's default branch
            go_left = jnp.where(jnp.isnan(xv), dl[tree_ids, idx], go_left)
        nxt = jnp.where(go_left, left[tree_ids, idx], right[tree_ids, idx])
        return jnp.where(f == LEAF, idx, nxt)

    idx0 = jnp.zeros((n, t), dtype=jnp.int32)
    idx = jax.lax.fori_loop(0, forest.max_depth, body, idx0)
    leaf_vals = value[tree_ids, idx]  # (N, T)

    def acc_body(ti, acc):
        return acc + leaf_vals[:, ti]

    return jax.lax.fori_loop(0, t, acc_body,
                             jnp.zeros(n, dtype=leaf_vals.dtype))


def finalize_margin(margin: np.ndarray, forest: FlatForest) -> np.ndarray:
    """SHARED host finalization margin -> TREE_SCORE — the single place
    that turns a canonical-order leaf sum into the score, used by BOTH
    scoring engines so the final bits cannot depend on the engine.

    ``mean`` divides (IEEE division is correctly rounded, so either side
    could do it); ``logit_sum`` applies the sigmoid HERE because exp is
    implementation-defined — XLA's logistic and libm's expf disagree in
    the last ulp on ~4% of inputs, so neither engine may bake it in.
    """
    m = np.asarray(margin, dtype=np.float32)
    if forest.aggregation == "mean":
        return m / np.float32(forest.n_trees)
    if forest.aggregation == "logit_sum":
        z = m + np.float32(forest.base_score)
        return (np.float32(1.0) / (np.float32(1.0) + np.exp(-z))).astype(np.float32)
    raise ValueError(f"unknown aggregation {forest.aggregation!r}")


def predict_score(forest: FlatForest, x: jnp.ndarray) -> jnp.ndarray:
    """TREE_SCORE in [0,1] for a (N, F) feature matrix (jit-safe).

    Device-finalized convenience wrapper over :func:`predict_margin` —
    accelerator callers keep everything on device. The engine-parity
    paths (pipelines/filter_variants) instead fetch the margin and
    finalize on the host via :func:`finalize_margin`, because the device
    sigmoid's exp is not bit-portable.
    """
    margin = predict_margin(forest, x)
    if forest.aggregation == "mean":
        return margin / forest.n_trees
    if forest.aggregation == "logit_sum":
        return jax.nn.sigmoid(margin + forest.base_score)
    raise ValueError(f"unknown aggregation {forest.aggregation!r}")


@dataclass
class GemmForest:
    """MXU-friendly forest encoding (Hummingbird-style GEMM strategy).

    Tree traversal recast as matmuls so inference rides the systolic array
    instead of XLA's (slow on TPU) dynamic gathers:

      XF    = X @ A          (N,F)@(F,I) one-hot feature pick per internal node
      D     = XF <= thr      {0,1} decisions
      match = D @ M2 + c     (N,I)@(I,L); M2 = 2*B - P with B[i,l]=1 iff leaf
                             l sits in i's LEFT subtree, P[i,l]=1 iff i is on
                             l's path; c[l] = #right-turns on l's path
      leaf  = (match == path_len)   — exactly one leaf matches
      score = leaf @ value

    All matmul operands are small exact integers (|M2|<=1, path sums <=
    depth), so the routing matmuls are bit-exact even in bf16; the feature
    pick runs at HIGHEST precision to keep threshold compares faithful.
    """

    a: np.ndarray  # f32 (T, F, I) one-hot feature selectors
    thr: np.ndarray  # f32 (T, I)
    m2: np.ndarray  # f32 (T, I, L) = 2B - P
    c: np.ndarray  # f32 (T, L) right-turn counts
    plen: np.ndarray  # f32 (T, L); -1 for padded leaves
    value: np.ndarray  # f32 (T, L)
    aggregation: str
    base_score: float
    # missing routing: None when the source forest has no default_left bits
    # (no NaN machinery in the compiled program); else f32 (T, I) 0/1
    dleft: np.ndarray | None = None

    @property
    def n_leaves(self) -> int:
        return self.m2.shape[2]


def to_gemm(forest: FlatForest, n_features: int | None = None) -> GemmForest:
    """Rewrite a FlatForest into path-matrix (GEMM) form (host-side, once)."""
    t = forest.n_trees
    n_features = int(n_features if n_features is not None else max(int(forest.feature.max()) + 1, 1))
    per_tree = []
    max_i, max_l = 1, 1
    for ti in range(t):
        feat, left, right = forest.feature[ti], forest.left[ti], forest.right[ti]
        internals: list[int] = []
        leaves: list[int] = []
        paths: list[list[tuple[int, bool]]] = []
        stack: list[tuple[int, list[tuple[int, bool]]]] = [(0, [])]
        while stack:
            node, path = stack.pop()
            if feat[node] == LEAF:
                leaves.append(node)
                paths.append(path)
            else:
                k = len(internals)
                internals.append(node)
                stack.append((int(right[node]), path + [(k, False)]))
                stack.append((int(left[node]), path + [(k, True)]))
        per_tree.append((internals, leaves, paths))
        max_i = max(max_i, len(internals))
        max_l = max(max_l, len(leaves))
    a = np.zeros((t, n_features, max_i), dtype=np.float32)
    thr = np.zeros((t, max_i), dtype=np.float32)
    m2 = np.zeros((t, max_i, max_l), dtype=np.float32)
    c = np.zeros((t, max_l), dtype=np.float32)
    plen = np.full((t, max_l), -1.0, dtype=np.float32)  # -1: padded leaf never matches
    value = np.zeros((t, max_l), dtype=np.float32)
    dleft = None if forest.default_left is None else np.zeros((t, max_i), dtype=np.float32)
    for ti, (internals, leaves, paths) in enumerate(per_tree):
        for k, node in enumerate(internals):
            a[ti, forest.feature[ti, node], k] = 1.0
            thr[ti, k] = forest.threshold[ti, node]
            if dleft is not None:
                dleft[ti, k] = float(forest.default_left[ti, node])
        for j, (node, path) in enumerate(zip(leaves, paths)):
            value[ti, j] = forest.value[ti, node]
            plen[ti, j] = len(path)
            for k, went_left in path:
                m2[ti, k, j] = 1.0 if went_left else -1.0  # 2B-P: left=+1, right=-1
                if not went_left:
                    c[ti, j] += 1.0
    return GemmForest(a, thr, m2, c, plen, value, forest.aggregation, forest.base_score,
                      dleft=dleft)


# beyond this many leaves per tree the (N,I)@(I,L) routing matmul costs more
# than the gather walk saves; fall back to the gather traversal
GEMM_MAX_LEAVES = 512


def predict_score_gemm(gf: GemmForest, x: jnp.ndarray) -> jnp.ndarray:
    """TREE_SCORE via the matmul formulation (jit/pjit-safe, MXU-bound).

    Scans over trees so peak memory is O(N * (I+L)) rather than
    O(T * N * L): each step is two (N,·)@(·,·) matmuls that tile cleanly
    onto the systolic array.
    """
    missing = gf.dleft is not None
    tables = (
        jnp.asarray(gf.a),
        jnp.asarray(gf.thr),
        jnp.asarray(gf.m2),
        jnp.asarray(gf.c),
        jnp.asarray(gf.plen),
        jnp.asarray(gf.value),
    ) + ((jnp.asarray(gf.dleft),) if missing else ())
    if missing:
        # NaN would poison every xf entry through the feature-pick matmul;
        # pick from a scrubbed copy and matmul the NaN mask through the
        # same selector to know, per node, whether its feature was missing
        x_miss = jnp.isnan(x).astype(jnp.float32)
        x = jnp.nan_to_num(x, nan=0.0)

    def per_tree(acc, tree):
        a, thr, m2, c, plen, value = tree[:6]
        # one-hot feature pick must preserve f32 values exactly: default
        # matmul precision rounds operands to bf16
        xf = jnp.dot(x, a, precision=jax.lax.Precision.HIGHEST)  # (N,I)
        d = (xf <= thr[None, :]).astype(jnp.float32)
        if missing:  # 0/1 mask matmul is exact even in bf16
            mf = jnp.dot(x_miss, a)  # (N,I) 1 where the node's feature is NaN
            d = jnp.where(mf > 0.5, tree[6][None, :], d)
        # routing matmul: operands are small exact integers — bf16-safe
        match = jnp.dot(d, m2) + c[None, :]  # (N,L)
        onehot = (match == plen[None, :]).astype(jnp.float32)
        s = jnp.dot(onehot, value, precision=jax.lax.Precision.HIGHEST)  # (N,)
        return acc + s, None

    total, _ = jax.lax.scan(per_tree, jnp.zeros(x.shape[0], dtype=jnp.float32), tables)
    if gf.aggregation == "mean":
        return total / gf.m2.shape[0]
    if gf.aggregation == "logit_sum":
        return jax.nn.sigmoid(total + gf.base_score)
    raise ValueError(f"unknown aggregation {gf.aggregation!r}")


#: Strategy chosen by the most recent make_predictor call — bench logs it
#: so a silent pallas->gemm (or gemm->gather) fallback is visible in the
#: captured perf evidence instead of invisibly changing what was measured.
last_strategy: str = "none"


def make_predictor(forest: FlatForest, n_features: int | None = None):
    """Best inference strategy for the active backend: the pallas fused
    per-tree kernel on TPU (VCTPU_PALLAS=0 opts out), the jnp GEMM
    encoding on other accelerators, the gather walk on CPU / big trees
    (the filter pipeline routes CPU single-device scoring through the
    native C++ walk before reaching here). Returns a jittable fn(x) ->
    scores; records the choice in :data:`last_strategy`."""
    import os

    global last_strategy
    gf = to_gemm(forest, n_features)
    try:
        backend = jax.default_backend()
    except Exception:  # backend init failure must not break program construction
        backend = "cpu"
    use_gemm = gf.n_leaves <= GEMM_MAX_LEAVES and backend != "cpu"
    if use_gemm:
        if backend == "tpu" and os.environ.get("VCTPU_PALLAS", "1") != "0":
            try:
                from variantcalling_tpu.models.forest_pallas import make_gemm_pallas_predictor

                fn = make_gemm_pallas_predictor(gf)
                # lowering failures only surface at the first call — warm up
                # HERE so the documented fallback holds for every caller,
                # not just ones that wrap their own calls
                n_feat = gf.a.shape[1]
                jax.block_until_ready(jax.jit(fn)(jnp.zeros((1, n_feat), jnp.float32)))
                last_strategy = "pallas"
                return fn
            except Exception:  # noqa: BLE001 — kernel gaps fall back to jnp GEMM
                pass
        last_strategy = "gemm"
        return lambda x: predict_score_gemm(gf, x)
    last_strategy = "gather"
    return lambda x: predict_score(forest, x)


def native_host_predictor(forest: FlatForest, strict: bool = False):
    """CPU fast path: the exact predict_score walk in C++ as a plain HOST
    function (numpy in, numpy out) — ~5x XLA:CPU's fused-gather lowering
    on one core. Callers split their program at the feature matrix and
    run this outside jit (a pure_callback inside the async chunk pipeline
    can deadlock XLA:CPU's single-threaded callback executor). Returns
    None when the native library is unavailable or the aggregation is
    unknown; use only on the CPU backend (accelerators keep GEMM/pallas).

    ``strict=True`` (the pinned-native engine paths): a mid-run native
    failure RAISES instead of silently computing the margin via XLA —
    an output stamped ``##vctpu_engine=native`` must never contain
    jit-scored rows (engine contract, docs/robustness.md)."""
    from variantcalling_tpu import native

    if not native.available() or forest.aggregation not in ("mean", "logit_sum"):
        return None
    feat = np.ascontiguousarray(forest.feature, dtype=np.int32)
    thr = np.ascontiguousarray(forest.threshold, dtype=np.float32)
    left = np.ascontiguousarray(forest.left, dtype=np.int32)
    right = np.ascontiguousarray(forest.right, dtype=np.int32)
    value = np.ascontiguousarray(forest.value, dtype=np.float32)
    dl = None if forest.default_left is None else \
        np.ascontiguousarray(forest.default_left, dtype=np.uint8)
    depth = forest.max_depth

    def fn(x: np.ndarray) -> np.ndarray:
        # raw canonical-order sums from the C++ walk; finalization happens
        # in the SHARED host code so the bits match the jit engine exactly
        margin = native.forest_predict(np.asarray(x), feat, thr, left, right,
                                       value, dl, depth, "sum", 0.0)
        if margin is None:
            if strict:
                from variantcalling_tpu.engine import EngineError

                raise EngineError(
                    "the native forest walk failed mid-run with the engine "
                    "pinned to native — refusing to silently score on the "
                    "jit walk. See docs/robustness.md.")
            # opportunistic callers: jnp walk fallback (bit-identical, the
            # canonical-order margin is engine-independent by construction)
            margin = np.asarray(predict_margin(forest, jnp.asarray(x)))
        return finalize_margin(margin, forest)

    return fn


def native_cols_predictor(forest: FlatForest):
    """CPU fast path over raw feature COLUMNS: the native engine tiles the
    column->matrix transpose L2-resident and walks each tile immediately,
    so the (n, f) float32 matrix never materializes (at 5M x 19 that is
    ~760 MB of skipped DRAM traffic vs build_matrix + the row walk).
    Bit-identical scores to :func:`native_host_predictor`. Returns None
    when unavailable; fn returns None when a column dtype is unsupported
    (caller falls back to the two-step path)."""
    from variantcalling_tpu import native

    if not native.available() or forest.aggregation not in ("mean", "logit_sum"):
        return None
    feat = np.ascontiguousarray(forest.feature, dtype=np.int32)
    thr = np.ascontiguousarray(forest.threshold, dtype=np.float32)
    left = np.ascontiguousarray(forest.left, dtype=np.int32)
    right = np.ascontiguousarray(forest.right, dtype=np.int32)
    value = np.ascontiguousarray(forest.value, dtype=np.float32)
    dl = None if forest.default_left is None else \
        np.ascontiguousarray(forest.default_left, dtype=np.uint8)
    depth = forest.max_depth

    def fn(cols: list[np.ndarray]) -> np.ndarray | None:
        margin = native.matrix_forest_predict(cols, feat, thr, left, right, value,
                                              dl, depth, "sum", 0.0)
        if margin is None:
            return None
        return finalize_margin(margin, forest)

    return fn


def from_sklearn(clf, feature_names: list[str] | None = None, pass_threshold: float = 0.5) -> FlatForest:
    """Flatten a fitted sklearn RandomForestClassifier/DecisionTree ensemble.

    Faithful to sklearn semantics: split is ``x[f] <= threshold`` goes left
    (sklearn uses <=); leaf value = class-1 fraction of training samples in
    the leaf; prediction = mean over trees (predict_proba).
    """
    raw = getattr(clf, "estimators_", None)
    if raw is None:
        estimators = [clf]
    elif isinstance(raw, np.ndarray):
        # GradientBoosting stores an (n_stages, n_classes) ndarray of
        # regressor trees -> boosted-margin aggregation, not mean-proba
        if raw.ndim == 2 and raw.shape[1] != 1:
            raise ValueError("only binary-class boosted ensembles are supported")
        return _from_sklearn_gbt(clf, raw.ravel().tolist(), feature_names, pass_threshold)
    else:
        estimators = list(raw)
    n_nodes = [e.tree_.node_count for e in estimators]
    m = max(n_nodes)
    t = len(estimators)
    feature = np.full((t, m), LEAF, dtype=np.int32)
    threshold = np.zeros((t, m), dtype=np.float32)
    left = np.zeros((t, m), dtype=np.int32)
    right = np.zeros((t, m), dtype=np.int32)
    value = np.zeros((t, m), dtype=np.float32)
    max_depth = 1
    for ti, est in enumerate(estimators):
        tr = est.tree_
        nc = tr.node_count
        f = tr.feature.astype(np.int32)
        is_leaf = tr.children_left == -1
        feature[ti, :nc] = np.where(is_leaf, LEAF, f)
        # sklearn compares float32-cast x against float64 thresholds; storing
        # the largest f32 <= threshold keeps `x <= thr` decisions bit-identical
        thr64 = tr.threshold
        thr32 = thr64.astype(np.float32)
        too_big = thr32.astype(np.float64) > thr64
        thr32[too_big] = np.nextafter(thr32[too_big], np.float32(-np.inf))
        threshold[ti, :nc] = thr32
        node_ids = np.arange(nc, dtype=np.int32)
        left[ti, :nc] = np.where(is_leaf, node_ids, tr.children_left)
        right[ti, :nc] = np.where(is_leaf, node_ids, tr.children_right)
        counts = tr.value[:, 0, :]  # (nc, n_classes) — class sample fractions
        if counts.shape[1] == 2:
            denom = counts.sum(axis=1)
            value[ti, :nc] = np.where(denom > 0, counts[:, 1] / np.maximum(denom, 1e-12), 0.0)
        else:
            # degenerate single-class fit: every leaf predicts that class
            classes = getattr(est, "classes_", getattr(clf, "classes_", np.array([1])))
            value[ti, :nc] = 1.0 if classes[0] == 1 else 0.0
        max_depth = max(max_depth, int(tr.max_depth))
    return FlatForest(
        feature=feature,
        threshold=threshold,
        left=left,
        right=right,
        value=value,
        max_depth=max_depth,
        aggregation="mean",
        feature_names=feature_names or [],
        pass_threshold=pass_threshold,
    )


def _from_sklearn_gbt(clf, trees: list, feature_names: list[str] | None, pass_threshold: float) -> FlatForest:
    """Flatten a fitted binary GradientBoostingClassifier.

    score = sigmoid(init_log_odds + lr * sum(tree margins)) — matches
    sklearn's staged decision function for the log-loss binary case.
    """
    lr = float(getattr(clf, "learning_rate", 1.0))
    base = 0.0
    init = getattr(clf, "init_", None)
    if init is not None and hasattr(init, "class_prior_"):
        p1 = float(np.clip(init.class_prior_[-1], 1e-12, 1 - 1e-12))
        base = float(np.log(p1 / (1 - p1)))
    m = max(t.tree_.node_count for t in trees)
    t_n = len(trees)
    feature = np.full((t_n, m), LEAF, dtype=np.int32)
    threshold = np.zeros((t_n, m), dtype=np.float32)
    left = np.zeros((t_n, m), dtype=np.int32)
    right = np.zeros((t_n, m), dtype=np.int32)
    value = np.zeros((t_n, m), dtype=np.float32)
    max_depth = 1
    for ti, est in enumerate(trees):
        tr = est.tree_
        nc = tr.node_count
        is_leaf = tr.children_left == -1
        feature[ti, :nc] = np.where(is_leaf, LEAF, tr.feature.astype(np.int32))
        thr64 = tr.threshold
        thr32 = thr64.astype(np.float32)
        too_big = thr32.astype(np.float64) > thr64
        thr32[too_big] = np.nextafter(thr32[too_big], np.float32(-np.inf))
        threshold[ti, :nc] = thr32
        node_ids = np.arange(nc, dtype=np.int32)
        left[ti, :nc] = np.where(is_leaf, node_ids, tr.children_left)
        right[ti, :nc] = np.where(is_leaf, node_ids, tr.children_right)
        value[ti, :nc] = lr * tr.value[:, 0, 0]
        max_depth = max(max_depth, int(tr.max_depth))
    return FlatForest(
        feature=feature,
        threshold=threshold,
        left=left,
        right=right,
        value=value,
        max_depth=max_depth,
        aggregation="logit_sum",
        base_score=base,
        feature_names=feature_names or [],
        pass_threshold=pass_threshold,
    )


def with_feature_order(forest: FlatForest, feature_names: list[str]) -> FlatForest:
    """Remap node feature indices to a new feature-column order."""
    if not forest.feature_names or forest.feature_names == feature_names:
        return forest
    mapping = np.asarray([feature_names.index(f) for f in forest.feature_names], dtype=np.int32)
    new_feat = np.where(forest.feature == LEAF, LEAF, mapping[np.maximum(forest.feature, 0)])
    return replace(forest, feature=new_feat.astype(np.int32), feature_names=list(feature_names))
