"""Neural variant-filter model (deep averaging network) — the MXU-native
model family.

The reference's model families are random forest + threshold models
(docs/howto-callset-filter.md). This framework adds a TPU-first family: an
embedding + MLP scorer over the same per-variant features — motif codes get
learned embeddings, numeric features are normalized, and the network runs
in bfloat16 on the MXU. Training is a standard optax step, sharded dp
(variants) × mp (hidden) over the mesh; gradient reduction is XLA-inserted
psum over dp, matching BASELINE config 3's sharded-fit requirement.

Precedent for DAN-style scoring of variants: "Genome Variant Calling with
a Deep Averaging Network" (PAPERS.md).
"""

from __future__ import annotations

import dataclasses
import hashlib
from dataclasses import dataclass, field
from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
import optax

from variantcalling_tpu.parallel.mesh import MODEL_AXIS

MOTIF_VOCAB = 5**5  # base-5 packed 5-mers (A,C,G,T,N)

FAMILY = "dan"
FAMILY_HEADER_KEY = "vctpu_model_family"


@dataclass(frozen=True)
class DanConfig:
    n_numeric: int  # numeric feature count (feature matrix minus motif columns)
    embed_dim: int = 16
    hidden: int = 256
    n_layers: int = 2
    dtype: str = "bfloat16"
    learning_rate: float = 1e-3
    weight_decay: float = 1e-4


def init_params(cfg: DanConfig, key: jax.Array) -> dict:
    k_embed, k_in, *k_hidden = jax.random.split(key, cfg.n_layers + 2)
    in_dim = cfg.n_numeric + 2 * cfg.embed_dim
    params = {
        "motif_embed": jax.random.normal(k_embed, (MOTIF_VOCAB, cfg.embed_dim)) * 0.02,
        "w_in": jax.random.normal(k_in, (in_dim, cfg.hidden)) * (1.0 / np.sqrt(in_dim)),
        "b_in": jnp.zeros((cfg.hidden,)),
        "w_out": jnp.zeros((cfg.hidden, 1)),
        "b_out": jnp.zeros((1,)),
    }
    for i, k in enumerate(k_hidden[: cfg.n_layers - 1]):
        params[f"w_{i}"] = jax.random.normal(k, (cfg.hidden, cfg.hidden)) * (1.0 / np.sqrt(cfg.hidden))
        params[f"b_{i}"] = jnp.zeros((cfg.hidden,))
    return params


def param_shardings(cfg: DanConfig, mesh) -> dict:
    """NamedShardings: hidden axis tensor-parallel over mp, embeddings replicated."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    s = {
        "motif_embed": NamedSharding(mesh, P(None, None)),
        "w_in": NamedSharding(mesh, P(None, MODEL_AXIS)),
        "b_in": NamedSharding(mesh, P(MODEL_AXIS)),
        "w_out": NamedSharding(mesh, P(MODEL_AXIS, None)),
        "b_out": NamedSharding(mesh, P(None)),
    }
    for i in range(cfg.n_layers - 1):
        s[f"w_{i}"] = NamedSharding(mesh, P(MODEL_AXIS, None))
        s[f"b_{i}"] = NamedSharding(mesh, P(None))
    return s


def forward(cfg: DanConfig, params: dict, numeric: jnp.ndarray, motif_left: jnp.ndarray,
            motif_right: jnp.ndarray) -> jnp.ndarray:
    """Logit per variant. numeric (N, n_numeric) f32; motifs int32 in [0, 5^5)."""
    dtype = jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32
    emb_l = params["motif_embed"][motif_left]
    emb_r = params["motif_embed"][motif_right]
    x = jnp.concatenate([numeric, emb_l, emb_r], axis=1).astype(dtype)
    h = jax.nn.gelu(x @ params["w_in"].astype(dtype) + params["b_in"].astype(dtype))
    for i in range(cfg.n_layers - 1):
        h = jax.nn.gelu(h @ params[f"w_{i}"].astype(dtype) + params[f"b_{i}"].astype(dtype))
    logit = h @ params["w_out"].astype(dtype) + params["b_out"].astype(dtype)
    return logit[:, 0].astype(jnp.float32)


def predict_score(cfg: DanConfig, params: dict, numeric, motif_left, motif_right) -> jnp.ndarray:
    return jax.nn.sigmoid(forward(cfg, params, numeric, motif_left, motif_right))


def make_optimizer(cfg: DanConfig):
    return optax.adamw(cfg.learning_rate, weight_decay=cfg.weight_decay)


def loss_fn(cfg: DanConfig, params: dict, batch: dict) -> jnp.ndarray:
    """Masked BCE over valid rows; `weight` supports exome upweighting
    (reference --exome_weight semantics, docs/train_models_pipeline.md)."""
    logits = forward(cfg, params, batch["numeric"], batch["motif_left"], batch["motif_right"])
    losses = optax.sigmoid_binary_cross_entropy(logits, batch["label"])
    w = batch.get("weight")
    if w is None:
        w = jnp.ones_like(losses)
    valid = batch.get("valid")
    if valid is not None:
        w = w * valid.astype(w.dtype)
    return jnp.sum(losses * w) / jnp.maximum(jnp.sum(w), 1.0)


@partial(jax.jit, static_argnums=(0, 1))
def train_step(cfg: DanConfig, optimizer, params: dict, opt_state, batch: dict):
    loss, grads = jax.value_and_grad(lambda p: loss_fn(cfg, p, batch))(params)
    updates, opt_state = optimizer.update(grads, opt_state, params)
    params = optax.apply_updates(params, updates)
    return params, opt_state, loss


@dataclass
class DanModel:
    """Pickle-able container compatible with the model registry."""

    cfg: DanConfig
    params_np: dict  # numpy copies of params
    feature_names: list[str] = field(default_factory=list)
    numeric_features: list[str] = field(default_factory=list)
    pass_threshold: float = 0.5
    norm_mu: np.ndarray | None = None  # numeric normalization (train_dan)
    norm_sd: np.ndarray | None = None

    def params(self) -> dict:
        return {k: jnp.asarray(v) for k, v in self.params_np.items()}

    @staticmethod
    def from_params(cfg, params, feature_names, numeric_features, pass_threshold=0.5) -> "DanModel":
        return DanModel(
            cfg=cfg,
            params_np={k: np.asarray(v) for k, v in params.items()},
            feature_names=list(feature_names),
            numeric_features=list(numeric_features),
            pass_threshold=pass_threshold,
        )


def weights_digest(model: DanModel) -> str:
    """Content address of a DAN's weights + scoring-relevant metadata.

    Feeds the scoring identity (io/identity.py): two DAN runs share
    journal/cache entries only when config, params, feature layout and
    normalization all match byte-for-byte — the model FILE signature
    alone cannot distinguish two families living in one pickle."""
    h = hashlib.sha256()
    h.update(repr(model.cfg).encode())
    h.update(repr((model.feature_names, model.numeric_features,
                   float(model.pass_threshold))).encode())
    for k in sorted(model.params_np):
        a = np.ascontiguousarray(model.params_np[k])
        h.update(k.encode())
        h.update(str(a.dtype).encode())
        h.update(repr(a.shape).encode())
        h.update(a.tobytes())
    for norm in (model.norm_mu, model.norm_sd):
        if norm is None:
            h.update(b"none")
        else:
            h.update(np.ascontiguousarray(norm, np.float32).tobytes())
    return h.hexdigest()


def make_score_predictor(model: DanModel, feature_names: list[str]):
    """Fused GEMM score program over the run's stacked (N, F) f32 feature
    matrix — the DAN twin of ``forest.make_margin_predictor``.

    Column selection is precomputed by NAME against the run's feature
    layout (a positional mismatch would silently score wrong columns);
    the forward pass is forced to f32 end-to-end so scores are
    bit-identical across batch buckets, padding, io threads and mesh
    device counts — the bfloat16 training dtype is a fit-time choice,
    not a serving contract. Motif codes arrive as f32 feature columns
    (exact integers < 5^5, all f32-representable) and are cast back to
    int32 embedding indices here."""
    idx = {f: i for i, f in enumerate(feature_names)}
    needed = [*model.numeric_features, "left_motif", "right_motif"]
    missing = [f for f in needed if f not in idx]
    if missing:
        from variantcalling_tpu.engine import EngineError

        raise EngineError(
            f"dan model needs feature(s) {missing} absent from the run's "
            f"feature layout {sorted(idx)}")
    cfg32 = dataclasses.replace(model.cfg, dtype="float32")
    num_idx = jnp.asarray([idx[f] for f in model.numeric_features], jnp.int32)
    li, ri = idx["left_motif"], idx["right_motif"]
    params32 = {k: jnp.asarray(np.asarray(v), jnp.float32)
                for k, v in model.params_np.items()}
    mu = None if model.norm_mu is None else jnp.asarray(model.norm_mu, jnp.float32)
    sd = (None if model.norm_sd is None
          else jnp.asarray(np.maximum(np.asarray(model.norm_sd, np.float32), 1e-6)))

    def program(x):
        numeric = jnp.take(x, num_idx, axis=1)
        if mu is not None:
            numeric = (numeric - mu) / sd
        ml = jnp.clip(x[:, li].astype(jnp.int32), 0, MOTIF_VOCAB - 1)
        mr = jnp.clip(x[:, ri].astype(jnp.int32), 0, MOTIF_VOCAB - 1)
        return predict_score(cfg32, params32, numeric, ml, mr)

    return program
