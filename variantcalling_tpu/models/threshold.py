"""Threshold model for somatic callsets (TLOD/SOR), re-derived from the docs.

The reference's somatic filter is "a simple model that uses TLOD and SOR of
the variant to assign confidence score TREE_SCORE"
(docs/howto-callset-filter.md:129-139, model name
``threshold_model_ignore_gt_incl_hpol_runs``). The internal code is in the
missing ugbio_filtering submodule; this implementation defines the model as
a per-feature soft margin: each feature contributes
``sigmoid((x - thr) * sign / scale)`` and TREE_SCORE is the product —
monotone in each feature, 0.5 at the threshold, hard PASS at
``score >= pass_threshold``.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

import jax
import jax.numpy as jnp


@dataclass
class ThresholdModel:
    feature_names: list[str]  # features used, in order of thresholds
    thresholds: np.ndarray  # float32 (F,)
    signs: np.ndarray  # +1 = higher is better, -1 = lower is better
    scales: np.ndarray  # softness per feature
    pass_threshold: float = 0.5
    all_feature_names: list[str] = field(default_factory=list)  # column order of X

    def column_indices(self, feature_names: list[str]) -> np.ndarray:
        return np.asarray([feature_names.index(f) for f in self.feature_names], dtype=np.int32)


def predict_score(model: ThresholdModel, x: jnp.ndarray, feature_names: list[str] | None = None) -> jnp.ndarray:
    """TREE_SCORE in [0,1] for (N, F) features (jit-safe)."""
    names = feature_names or model.all_feature_names or model.feature_names
    cols = model.column_indices(names)
    xs = x[:, cols]
    margins = (xs - jnp.asarray(model.thresholds)) * jnp.asarray(model.signs) / jnp.asarray(model.scales)
    return jnp.prod(jax.nn.sigmoid(margins), axis=1)


def fit_threshold_model(
    x: np.ndarray,
    y: np.ndarray,
    feature_names: list[str],
    candidate_features: list[str] | None = None,
    sample_weight: np.ndarray | None = None,
    n_grid: int = 24,
    pass_threshold: float = 0.25,
) -> ThresholdModel:
    """Fit thresholds by exhaustive grid search — one device pass.

    For each used feature, candidate thresholds are quantiles of its
    distribution and the sign is chosen by class-mean direction; the joint
    grid (n_grid^F combinations for the 2-feature somatic case) is scored
    in a single (N, G) batched evaluation and the max-F1 cell wins —
    the TPU-native analog of the reference's hand-tuned TLOD/SOR cuts
    (docs/howto-callset-filter.md:129-139).
    """
    cand = [f for f in (candidate_features or ["tlod", "sor"]) if f in feature_names]
    if not cand:  # fall back to the two strongest features by |corr|
        corr = [abs(float(np.corrcoef(x[:, i], y)[0, 1])) if np.std(x[:, i]) > 0 else 0.0 for i in range(x.shape[1])]
        cand = [feature_names[i] for i in np.argsort(corr)[::-1][:2]]
    cols = [feature_names.index(f) for f in cand]
    xs = np.asarray(x[:, cols], dtype=np.float32)  # (N, F)
    yv = np.asarray(y, dtype=np.float32)
    wv = np.ones(len(y), np.float32) if sample_weight is None else np.asarray(sample_weight, np.float32)
    if len(xs) > 500_000:  # the (N, G^F) sweep is memory-bound; a 500K
        sel = np.random.default_rng(0).choice(len(xs), 500_000, replace=False)  # subsample loses no precision here
        xs, yv, wv = xs[sel], yv[sel], wv[sel]
    yb = jnp.asarray(yv)
    w = jnp.asarray(wv)

    pos, neg = yv > 0.5, yv <= 0.5
    if not pos.any() or not neg.any():
        signs = np.ones(xs.shape[1], dtype=np.float32)
    else:
        signs = np.array(
            [1.0 if xs[pos, j].mean() >= xs[neg, j].mean() else -1.0 for j in range(xs.shape[1])],
            dtype=np.float32,
        )
    qs = np.linspace(0.02, 0.98, n_grid)
    cand_thr = np.quantile(xs, qs, axis=0).astype(np.float32)  # (G, F)
    # joint grid over per-feature candidates
    grids = np.meshgrid(*[cand_thr[:, j] for j in range(xs.shape[1])], indexing="ij")
    combos = np.stack([g.ravel() for g in grids], axis=1)  # (G^F, F)

    @jax.jit
    def best_combo(xs_d, combos_d):
        # hard pass/fail per combo: all features on the good side
        ok = (xs_d[:, None, :] - combos_d[None, :, :]) * signs[None, None, :] >= 0  # (N, C, F)
        pred = jnp.all(ok, axis=2).astype(jnp.float32)  # (N, C)
        tp = (w * yb) @ pred
        fp = (w * (1 - yb)) @ pred
        fn = jnp.sum(w * yb) - tp
        f1 = 2 * tp / jnp.maximum(2 * tp + fp + fn, 1e-9)
        return jnp.argmax(f1)

    idx = int(best_combo(jnp.asarray(xs), jnp.asarray(combos)))
    thr = combos[idx]
    # sharp sigmoids keep the soft score close to the hard cut the grid
    # search optimized, while staying differentiable for downstream curves
    scales = np.maximum(np.std(xs, axis=0) * 0.05, 1e-3).astype(np.float32)
    return ThresholdModel(
        feature_names=cand,
        thresholds=thr.astype(np.float32),
        signs=signs,
        scales=scales,
        pass_threshold=pass_threshold,
        all_feature_names=list(feature_names),
    )


def default_somatic_model(all_feature_names: list[str]) -> ThresholdModel:
    """TLOD/SOR thresholds per the somatic howto (TLOD high good, SOR low good)."""
    return ThresholdModel(
        feature_names=["tlod", "sor"],
        thresholds=np.asarray([6.3, 3.0], dtype=np.float32),
        signs=np.asarray([1.0, -1.0], dtype=np.float32),
        scales=np.asarray([2.0, 1.0], dtype=np.float32),
        pass_threshold=0.25,
        all_feature_names=list(all_feature_names),
    )
