"""Threshold model for somatic callsets (TLOD/SOR), re-derived from the docs.

The reference's somatic filter is "a simple model that uses TLOD and SOR of
the variant to assign confidence score TREE_SCORE"
(docs/howto-callset-filter.md:129-139, model name
``threshold_model_ignore_gt_incl_hpol_runs``). The internal code is in the
missing ugbio_filtering submodule; this implementation defines the model as
a per-feature soft margin: each feature contributes
``sigmoid((x - thr) * sign / scale)`` and TREE_SCORE is the product —
monotone in each feature, 0.5 at the threshold, hard PASS at
``score >= pass_threshold``.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

import jax
import jax.numpy as jnp


@dataclass
class ThresholdModel:
    feature_names: list[str]  # features used, in order of thresholds
    thresholds: np.ndarray  # float32 (F,)
    signs: np.ndarray  # +1 = higher is better, -1 = lower is better
    scales: np.ndarray  # softness per feature
    pass_threshold: float = 0.5
    all_feature_names: list[str] = field(default_factory=list)  # column order of X

    def column_indices(self, feature_names: list[str]) -> np.ndarray:
        return np.asarray([feature_names.index(f) for f in self.feature_names], dtype=np.int32)


def predict_score(model: ThresholdModel, x: jnp.ndarray, feature_names: list[str] | None = None) -> jnp.ndarray:
    """TREE_SCORE in [0,1] for (N, F) features (jit-safe)."""
    names = feature_names or model.all_feature_names or model.feature_names
    cols = model.column_indices(names)
    xs = x[:, cols]
    margins = (xs - jnp.asarray(model.thresholds)) * jnp.asarray(model.signs) / jnp.asarray(model.scales)
    return jnp.prod(jax.nn.sigmoid(margins), axis=1)


def default_somatic_model(all_feature_names: list[str]) -> ThresholdModel:
    """TLOD/SOR thresholds per the somatic howto (TLOD high good, SOR low good)."""
    return ThresholdModel(
        feature_names=["tlod", "sor"],
        thresholds=np.asarray([6.3, 3.0], dtype=np.float32),
        signs=np.asarray([1.0, -1.0], dtype=np.float32),
        scales=np.asarray([2.0, 1.0], dtype=np.float32),
        pass_threshold=0.25,
        all_feature_names=list(all_feature_names),
    )
