"""Pallas TPU kernel for GEMM-forest inference.

The jnp formulation (models/forest.predict_score_gemm) scans trees with
three matmuls per step; each step's (N, I) decision and (N, L) routing
intermediates round-trip through HBM unless XLA happens to fuse them.
This kernel keeps the WHOLE per-tree chain in VMEM:

    grid = (variant tiles, trees); per step the (TILE_N, F) feature tile
    and tree t's tables sit in VMEM, and

        xf    = x @ a[t]          (MXU, HIGHEST precision feature pick)
        d     = xf <= thr[t]      (VPU)
        match = d @ m2[t] + c[t]  (MXU; exact small ints)
        hit   = match == plen[t]  (VPU)
        out  += hit @ value[t]    (MXU accumulate into the output block)

    Only the (TILE_N, 1) score block ever leaves VMEM — per-tree
    intermediates never touch HBM. Trees iterate innermost, so the output
    block revisits and accumulates (TPU grids run sequentially).

Two kernels live here:

- the original per-tree kernel (``make_gemm_pallas_predictor``): grid
  (variant tiles, trees), output block accumulates the margin across the
  sequential tree-innermost grid — kept for reference/fallback;
- the WIDE-BLOCK kernel (``make_wide_pallas_margin_predictor``): grid
  (variant tiles, tree blocks) over the block-diagonal wide encoding
  (``models/forest.to_wide``). Each step computes G trees per MXU pass
  and emits a (TILE_N, G) per-tree margin block; the canonical-order tree
  reduction runs OUTSIDE the kernel through the one shared
  ``forest.sequential_tree_sum``, so margins are bit-identical to the
  gather walk, the jnp GEMM paths and the native C++ engine.

Integration: the ``pallas`` entry of the models/forest strategy registry
(``VCTPU_FOREST_STRATEGY``; auto prefers it on TPU, VCTPU_PALLAS=0 opts
out) builds the wide-block kernel; CPU tests run the same kernels in
interpreter mode. Forests with missing-value routing (default_left) use
the jnp paths — NaN-bearing inputs need the extra mask matmul.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

TILE_N = 512


def _tree_step_kernel(x_ref, a_ref, thr_ref, m2_ref, c_ref, plen_ref, val_ref, out_ref):
    from jax.experimental import pallas as pl

    @pl.when(pl.program_id(1) == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    x = x_ref[:]  # (TILE_N, F)
    a = a_ref[0]  # (F, I)
    # feature pick must keep f32 values exact (thresholds compare tightly)
    xf = jax.lax.dot_general(x, a, (((1,), (0,)), ((), ())),
                             precision=jax.lax.Precision.HIGHEST,
                             preferred_element_type=jnp.float32)
    d = (xf <= thr_ref[0][None, :]).astype(jnp.float32)  # (TILE_N, I)
    # routing operands are exact small integers — default precision is safe
    match = jax.lax.dot_general(d, m2_ref[0], (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)
    match = match + c_ref[0][None, :]
    hit = (match == plen_ref[0][None, :]).astype(jnp.float32)  # (TILE_N, L)
    s = jax.lax.dot_general(hit, val_ref[0][:, None], (((1,), (0,)), ((), ())),
                            precision=jax.lax.Precision.HIGHEST,
                            preferred_element_type=jnp.float32)  # (TILE_N, 1)
    out_ref[:] += s


def _margin_pallas(tables, x, interpret: bool) -> jnp.ndarray:
    """Summed per-tree margins for a PADDED (N, F) f32 matrix."""
    from jax.experimental import pallas as pl

    a, thr, m2, c, plen, value = tables
    t, f, i = a.shape
    l = m2.shape[2]
    n = x.shape[0]
    assert n % TILE_N == 0

    out = pl.pallas_call(
        _tree_step_kernel,
        grid=(n // TILE_N, t),
        in_specs=[
            pl.BlockSpec((TILE_N, f), lambda bi, ti: (bi, 0)),
            pl.BlockSpec((1, f, i), lambda bi, ti: (ti, 0, 0)),
            pl.BlockSpec((1, i), lambda bi, ti: (ti, 0)),
            pl.BlockSpec((1, i, l), lambda bi, ti: (ti, 0, 0)),
            pl.BlockSpec((1, l), lambda bi, ti: (ti, 0)),
            pl.BlockSpec((1, l), lambda bi, ti: (ti, 0)),
            pl.BlockSpec((1, l), lambda bi, ti: (ti, 0)),
        ],
        out_specs=pl.BlockSpec((TILE_N, 1), lambda bi, ti: (bi, 0)),
        out_shape=jax.ShapeDtypeStruct((n, 1), jnp.float32),
        interpret=interpret,
    )(x, a, thr, m2, c, plen, value)
    return out[:, 0]


def _wide_block_kernel(x_ref, a_ref, thr_ref, m2_ref, c_ref, plen_ref,
                       val_ref, out_ref):
    """One (variant tile, tree block) step of the WIDE strategy: the whole
    per-block chain — wide feature pick, compare, block-diagonal routing,
    per-tree leaf pick — stays in VMEM; only the (TILE_N, G) per-tree
    margin block leaves. No cross-step accumulation: each grid step owns
    its output block, and the canonical-order tree reduction happens
    OUTSIDE the kernel through the shared forest.sequential_tree_sum."""
    x = x_ref[:]  # (TILE_N, F)
    a = a_ref[0]  # (F, G*I)
    # feature pick must keep f32 values exact (thresholds compare tightly)
    xf = jax.lax.dot_general(x, a, (((1,), (0,)), ((), ())),
                             precision=jax.lax.Precision.HIGHEST,
                             preferred_element_type=jnp.float32)
    d = (xf <= thr_ref[0][None, :]).astype(jnp.float32)  # (TILE_N, G*I)
    # block-diagonal routing: operands are exact small integers
    match = jax.lax.dot_general(d, m2_ref[0], (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)
    match = match + c_ref[0][None, :]
    hit = (match == plen_ref[0][None, :]).astype(jnp.float32)  # (TILE_N, G*L)
    val = val_ref[0]  # (G, L)
    g, l = val.shape
    # per-tree leaf pick on the VPU: exactly one hit per (variant, tree),
    # every other term is an exact +0.0 — bit-exact in any reduction order
    out_ref[:] = jnp.sum(hit.reshape(x.shape[0], g, l) * val[None, :, :],
                         axis=2)


def make_wide_pallas_margin_predictor(gf, tree_block: int | None = None,
                                      interpret: bool | None = None):
    """fn(x) -> canonical-order margin for a GemmForest, running the
    wide-block kernel (grid over (variant tile, tree block); all of a
    block's operands VMEM-resident).

    Raises ValueError for forests the kernel does not cover (missing-value
    routing); the auto strategy falls back to the jnp wide path, an
    explicit ``pallas`` request fails loudly (models/forest registry).
    """
    from jax.experimental import pallas as pl

    from variantcalling_tpu.models import forest as forest_mod

    if gf.dleft is not None:
        raise ValueError("pallas forest kernel does not implement default_left routing")
    if interpret is None:
        try:
            interpret = jax.default_backend() != "tpu"
        except Exception as e:  # noqa: BLE001
            from variantcalling_tpu.utils import degrade

            degrade.record("forest_pallas.backend_probe", e,
                           fallback="interpret=True")
            interpret = True
    wf = forest_mod.to_wide(gf, tree_block)
    b, f, gi = wf.a.shape
    gl = wf.m2.shape[2]
    g = wf.tree_block
    tables = (
        jnp.asarray(wf.a),
        jnp.asarray(wf.thr),
        jnp.asarray(wf.m2),
        jnp.asarray(wf.c),
        jnp.asarray(wf.plen),
        jnp.asarray(wf.value),
    )
    n_trees = wf.n_trees

    def predict(x):
        n = x.shape[0]
        if n == 0:  # a zero-size grid cannot dispatch
            return jnp.zeros((0,), jnp.float32)
        pad = (-n) % TILE_N
        xp = jnp.pad(x.astype(jnp.float32), ((0, pad), (0, 0)))
        per_tree = pl.pallas_call(
            _wide_block_kernel,
            grid=(xp.shape[0] // TILE_N, b),
            in_specs=[
                pl.BlockSpec((TILE_N, f), lambda bi, ti: (bi, 0)),
                pl.BlockSpec((1, f, gi), lambda bi, ti: (ti, 0, 0)),
                pl.BlockSpec((1, gi), lambda bi, ti: (ti, 0)),
                pl.BlockSpec((1, gi, gl), lambda bi, ti: (ti, 0, 0)),
                pl.BlockSpec((1, gl), lambda bi, ti: (ti, 0)),
                pl.BlockSpec((1, gl), lambda bi, ti: (ti, 0)),
                pl.BlockSpec((1, g, gl // g), lambda bi, ti: (ti, 0, 0)),
            ],
            out_specs=pl.BlockSpec((TILE_N, g), lambda bi, ti: (bi, ti)),
            out_shape=jax.ShapeDtypeStruct((xp.shape[0], b * g), jnp.float32),
            interpret=interpret,
        )(xp, *tables)
        return forest_mod.sequential_tree_sum(per_tree[:, :n_trees])[:n]

    return predict


def make_gemm_pallas_predictor(gf, interpret: bool | None = None):
    """fn(x) -> scores for a GemmForest, running the pallas kernel.

    Raises ValueError for forests the kernel does not cover (missing-value
    routing); callers fall back to the jnp GEMM path.
    """
    if gf.dleft is not None:
        raise ValueError("pallas forest kernel does not implement default_left routing")
    if interpret is None:
        try:
            interpret = jax.default_backend() != "tpu"
        except Exception as e:  # noqa: BLE001
            from variantcalling_tpu.utils import degrade

            degrade.record("forest_pallas.backend_probe", e,
                           fallback="interpret=True")
            interpret = True
    tables = (
        jnp.asarray(gf.a),
        jnp.asarray(gf.thr),
        jnp.asarray(gf.m2),
        jnp.asarray(gf.c),
        jnp.asarray(gf.plen),
        jnp.asarray(gf.value),
    )
    n_trees = gf.m2.shape[0]
    agg, base = gf.aggregation, gf.base_score

    def predict(x):
        n = x.shape[0]
        if n == 0:  # a zero-size grid cannot dispatch
            return jnp.zeros((0,), jnp.float32)
        pad = (-n) % TILE_N
        xp = jnp.pad(x.astype(jnp.float32), ((0, pad), (0, 0)))
        total = _margin_pallas(tables, xp, interpret)[:n]
        if agg == "mean":
            return total / n_trees
        if agg == "logit_sum":
            return jax.nn.sigmoid(total + base)
        raise ValueError(f"unknown aggregation {agg!r}")

    return predict
