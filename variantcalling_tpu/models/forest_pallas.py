"""Pallas TPU kernel for GEMM-forest inference.

The jnp formulation (models/forest.predict_score_gemm) scans trees with
three matmuls per step; each step's (N, I) decision and (N, L) routing
intermediates round-trip through HBM unless XLA happens to fuse them.
This kernel keeps the WHOLE per-tree chain in VMEM:

    grid = (variant tiles, trees); per step the (TILE_N, F) feature tile
    and tree t's tables sit in VMEM, and

        xf    = x @ a[t]          (MXU, HIGHEST precision feature pick)
        d     = xf <= thr[t]      (VPU)
        match = d @ m2[t] + c[t]  (MXU; exact small ints)
        hit   = match == plen[t]  (VPU)
        out  += hit @ value[t]    (MXU accumulate into the output block)

    Only the (TILE_N, 1) score block ever leaves VMEM — per-tree
    intermediates never touch HBM. Trees iterate innermost, so the output
    block revisits and accumulates (TPU grids run sequentially).

Integration: models/forest.make_predictor routes here on TPU backends
(VCTPU_PALLAS=0 opts out); CPU tests run the same kernel in interpreter
mode. Forests with missing-value routing (default_left) use the jnp path
— NaN-bearing inputs need the extra mask matmul.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

TILE_N = 512


def _tree_step_kernel(x_ref, a_ref, thr_ref, m2_ref, c_ref, plen_ref, val_ref, out_ref):
    from jax.experimental import pallas as pl

    @pl.when(pl.program_id(1) == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    x = x_ref[:]  # (TILE_N, F)
    a = a_ref[0]  # (F, I)
    # feature pick must keep f32 values exact (thresholds compare tightly)
    xf = jax.lax.dot_general(x, a, (((1,), (0,)), ((), ())),
                             precision=jax.lax.Precision.HIGHEST,
                             preferred_element_type=jnp.float32)
    d = (xf <= thr_ref[0][None, :]).astype(jnp.float32)  # (TILE_N, I)
    # routing operands are exact small integers — default precision is safe
    match = jax.lax.dot_general(d, m2_ref[0], (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)
    match = match + c_ref[0][None, :]
    hit = (match == plen_ref[0][None, :]).astype(jnp.float32)  # (TILE_N, L)
    s = jax.lax.dot_general(hit, val_ref[0][:, None], (((1,), (0,)), ((), ())),
                            precision=jax.lax.Precision.HIGHEST,
                            preferred_element_type=jnp.float32)  # (TILE_N, 1)
    out_ref[:] += s


def _margin_pallas(tables, x, interpret: bool) -> jnp.ndarray:
    """Summed per-tree margins for a PADDED (N, F) f32 matrix."""
    from jax.experimental import pallas as pl

    a, thr, m2, c, plen, value = tables
    t, f, i = a.shape
    l = m2.shape[2]
    n = x.shape[0]
    assert n % TILE_N == 0

    out = pl.pallas_call(
        _tree_step_kernel,
        grid=(n // TILE_N, t),
        in_specs=[
            pl.BlockSpec((TILE_N, f), lambda bi, ti: (bi, 0)),
            pl.BlockSpec((1, f, i), lambda bi, ti: (ti, 0, 0)),
            pl.BlockSpec((1, i), lambda bi, ti: (ti, 0)),
            pl.BlockSpec((1, i, l), lambda bi, ti: (ti, 0, 0)),
            pl.BlockSpec((1, l), lambda bi, ti: (ti, 0)),
            pl.BlockSpec((1, l), lambda bi, ti: (ti, 0)),
            pl.BlockSpec((1, l), lambda bi, ti: (ti, 0)),
        ],
        out_specs=pl.BlockSpec((TILE_N, 1), lambda bi, ti: (bi, 0)),
        out_shape=jax.ShapeDtypeStruct((n, 1), jnp.float32),
        interpret=interpret,
    )(x, a, thr, m2, c, plen, value)
    return out[:, 0]


def make_gemm_pallas_predictor(gf, interpret: bool | None = None):
    """fn(x) -> scores for a GemmForest, running the pallas kernel.

    Raises ValueError for forests the kernel does not cover (missing-value
    routing); callers fall back to the jnp GEMM path.
    """
    if gf.dleft is not None:
        raise ValueError("pallas forest kernel does not implement default_left routing")
    if interpret is None:
        try:
            interpret = jax.default_backend() != "tpu"
        except Exception:  # noqa: BLE001
            interpret = True
    tables = (
        jnp.asarray(gf.a),
        jnp.asarray(gf.thr),
        jnp.asarray(gf.m2),
        jnp.asarray(gf.c),
        jnp.asarray(gf.plen),
        jnp.asarray(gf.value),
    )
    n_trees = gf.m2.shape[0]
    agg, base = gf.aggregation, gf.base_score

    def predict(x):
        n = x.shape[0]
        pad = (-n) % TILE_N
        xp = jnp.pad(x.astype(jnp.float32), ((0, pad), (0, 0)))
        total = _margin_pallas(tables, xp, interpret)[:n]
        if agg == "mean":
            return total / n_trees
        if agg == "logit_sum":
            return jax.nn.sigmoid(total + base)
        raise ValueError(f"unknown aggregation {agg!r}")

    return predict
