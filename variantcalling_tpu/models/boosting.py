"""Histogram-based gradient-boosted trees, trained end-to-end on TPU.

The reference trains sklearn RandomForest / xgboost on CPU
(docs/train_models_pipeline.md, setup/environment.yml: xgboost 2.1.2) —
a per-node, pointer-chasing algorithm. This trainer is re-founded for the
MXU/XLA execution model:

- features are quantile-binned once (B bins), so every split decision is a
  histogram lookup, never a sort;
- trees are complete depth-D trees grown level-by-level, so every shape is
  static: per level, gradient/hessian histograms over (node, feature, bin)
  are segment-sums, split search is a cumsum + argmax, and sample routing
  is one gather — the entire fit of all T trees is ONE jitted
  ``lax.fori_loop`` program with zero host round-trips;
- under pjit, the sample axis shards across the mesh and XLA inserts the
  psum for each histogram (the "sharded training reductions" of BASELINE
  config 3) — the same program runs single-chip or on a pod.

The fitted model exports to :class:`~variantcalling_tpu.models.forest.
FlatForest` (aggregation="logit_sum"), so inference shares the filter
pipeline's gather-traversal kernel.
"""

from __future__ import annotations

from contextlib import nullcontext
from dataclasses import dataclass

import numpy as np

import jax
import jax.numpy as jnp

from variantcalling_tpu import knobs
from variantcalling_tpu.models.forest import LEAF, FlatForest
from variantcalling_tpu.utils import degrade


@dataclass(frozen=True)
class BoostConfig:
    n_trees: int = 100
    depth: int = 6
    n_bins: int = 64
    learning_rate: float = 0.15
    reg_lambda: float = 1.0
    min_child_weight: float = 1.0
    base_score: float = 0.0  # initial margin (log-odds)


def quantile_bin_edges(x: np.ndarray, n_bins: int, max_sample: int = 200_000, seed: int = 0) -> np.ndarray:
    """(F, n_bins-1) per-feature bin edges from (sub-sampled) quantiles."""
    n = x.shape[0]
    if n > max_sample:
        idx = np.random.default_rng(seed).choice(n, max_sample, replace=False)
        x = x[idx]
    qs = np.linspace(0, 1, n_bins + 1)[1:-1]
    edges = np.quantile(x, qs, axis=0).T.astype(np.float32)  # (F, B-1)
    # non-decreasing edges keep searchsorted well-defined; duplicate edges
    # (constant-ish features) just leave empty bins, which cost no gain
    return np.maximum.accumulate(edges, axis=1)


def bin_features(x: jnp.ndarray, edges: jnp.ndarray) -> jnp.ndarray:
    """(N, F) int32 bin ids in [0, B); device-side vectorized searchsorted."""
    return jax.vmap(lambda col, e: jnp.searchsorted(e, col), in_axes=(1, 0), out_axes=1)(x, edges).astype(jnp.int32)


#: Per-device byte cap for materializing the (N, F*B) bin one-hot once per
#: fit (shared by every level of every tree). Above it, the one-hot is
#: regenerated inside each level step instead — same results, more traffic.
BOH_RESIDENT_MAX_BYTES = 4 << 30


def _hist_matmul(binned, boh, gh16, node_id, n_nodes, f, b):
    """(node, feature, bin) g/h histograms as ONE MXU matmul:
    lhs (N, 2*2^l) carries g/h masked by node one-hot, rhs (N, F*B) is the
    per-feature bin one-hot — their contraction over N yields both
    gradient and hessian histograms at systolic-array rate. Under pjit the
    N contraction is where XLA inserts the cross-device psum (BASELINE
    config 3). bf16 operands, f32 accumulation: one-hot entries are exact
    in bf16; g/h lose ~3 decimal digits, far below split-gain contrasts."""
    n = binned.shape[0]
    noh = jax.nn.one_hot(node_id, n_nodes, dtype=jnp.bfloat16)  # (N, 2^l)
    lhs = (gh16[:, :, None] * noh[:, None, :]).reshape(n, 2 * n_nodes)
    rhs = boh if boh is not None else \
        jax.nn.one_hot(binned, b, dtype=jnp.bfloat16).reshape(n, f * b)
    hist2 = jax.lax.dot_general(
        lhs, rhs, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )  # (2*2^l, F*B)
    # lhs columns flatten as (gh, node) — index = gh * n_nodes + node —
    # so the row axis unpacks gh-major
    hist2 = hist2.reshape(2, n_nodes, f, b)
    return hist2[0], hist2[1]


def _hist_scatter_slab(binned, g, h, node_id, n_nodes, f, b):
    """One flat segment-sum over (node, feature, bin) ids for <=SLAB rows."""
    n = binned.shape[0]
    # id = node*(F*B) + feature*B + bin, one flat scatter for all features
    seg = (node_id[:, None] * (f * b) + jnp.arange(f, dtype=jnp.int32) * b
           + binned).reshape(-1)
    gh = jnp.broadcast_to(jnp.stack([g, h], -1)[:, None, :], (n, f, 2)).reshape(n * f, 2)
    ghs = jax.ops.segment_sum(gh, seg, num_segments=n_nodes * f * b)  # (nodes*F*b, 2)
    ghs = ghs.reshape(n_nodes, f, b, 2)
    return ghs[..., 0], ghs[..., 1]


#: rows per scatter slab: the flattened ids + (g,h) broadcast cost
#: ~12 B * rows * F of temporaries — at 5M x 19 an unchunked scatter
#: materializes ~1.5 GB; slabs bound it to ~120 MB.
_SCATTER_SLAB = 1 << 19


def _hist_scatter(binned, g, h, node_id, n_nodes, f, b):
    """The same histograms via fused segment-sums over (node, feature,
    bin) ids (scatter-add), in bounded row slabs.

    CPU-only strategy: scatter-add is fast there and skips the big bf16
    one-hot matmuls, while on TPU it would serialize (the documented ~60x
    cliff). A single flattened scatter over N*F elements runs ~1.7x
    faster on XLA CPU than F per-feature segment-sums. Sums accumulate
    in f32 like the matmul path; large N scans over slabs so the
    flattened temporaries stay ~120 MB regardless of N (padded rows
    carry g = h = 0, adding exactly nothing)."""
    n = binned.shape[0]
    if n <= _SCATTER_SLAB:
        return _hist_scatter_slab(binned, g, h, node_id, n_nodes, f, b)
    pad = (-n) % _SCATTER_SLAB
    if pad:
        binned = jnp.pad(binned, ((0, pad), (0, 0)))
        g = jnp.pad(g, (0, pad))
        h = jnp.pad(h, (0, pad))
        node_id = jnp.pad(node_id, (0, pad))
    k = (n + pad) // _SCATTER_SLAB
    slabs = (binned.reshape(k, _SCATTER_SLAB, f), g.reshape(k, _SCATTER_SLAB),
             h.reshape(k, _SCATTER_SLAB), node_id.reshape(k, _SCATTER_SLAB))

    def body(acc, sl):
        hg, hh = _hist_scatter_slab(sl[0], sl[1], sl[2], sl[3], n_nodes, f, b)
        return (acc[0] + hg, acc[1] + hh), None

    init = (jnp.zeros((n_nodes, f, b), g.dtype), jnp.zeros((n_nodes, f, b), h.dtype))
    (hist_g, hist_h), _ = jax.lax.scan(body, init, slabs)
    return hist_g, hist_h


def _grow_tree(binned, boh, g, h, cfg: BoostConfig, use_matmul: bool = True):
    """One complete depth-D tree. Returns (feat (D, L), bin (D, L), leaf (2^D,)).

    ``feat[l, k]`` / ``bin[l, k]`` describe the split of node k at level l
    (feat == -1: dead node, routes everything left); arrays are padded to
    L = 2^D nodes for static shapes. ``boh`` is the fit-wide (N, F*B) bin
    one-hot, or None to regenerate it per level (memory guard).

    The level loop is UNROLLED (depth is a small static constant): at level
    l only 2^l nodes exist, so the histogram matmul's lhs is (N, 2*2^l) —
    the per-tree FLOP count is half what a constant 2*2^D-wide lhs costs,
    and the dominant rhs read is amortized against one hoisted one-hot.
    ``use_matmul`` picks the histogram strategy (MXU matmul on
    accelerators, segment-sum scatter on CPU).
    """
    n, f = binned.shape
    b = cfg.n_bins
    max_nodes = 1 << cfg.depth  # leaves
    lam = cfg.reg_lambda

    gh16 = jnp.stack([g, h], 1).astype(jnp.bfloat16)  # (N, 2)
    node_id = jnp.zeros(n, dtype=jnp.int32)
    feat_rows, bin_rows = [], []
    for level in range(cfg.depth):
        n_nodes = 1 << level
        if use_matmul:
            hist_g, hist_h = _hist_matmul(binned, boh, gh16, node_id, n_nodes, f, b)
        else:
            hist_g, hist_h = _hist_scatter(binned, g, h, node_id, n_nodes, f, b)

        gl = jnp.cumsum(hist_g, axis=2)  # left sums for split at bin <= j
        hl = jnp.cumsum(hist_h, axis=2)
        gt = gl[:, :, -1:]
        ht = hl[:, :, -1:]
        gr = gt - gl
        hr = ht - hl
        parent = (gt * gt) / (ht + lam)
        gain = (gl * gl) / (hl + lam) + (gr * gr) / (hr + lam) - parent  # (node, F, B)
        ok = (hl >= cfg.min_child_weight) & (hr >= cfg.min_child_weight)
        gain = jnp.where(ok, gain, -jnp.inf)
        gain = gain.at[:, :, -1].set(-jnp.inf)  # last bin = no split
        flat = gain.reshape(n_nodes, f * b)
        best = jnp.argmax(flat, axis=1)
        best_gain = jnp.take_along_axis(flat, best[:, None], axis=1)[:, 0]
        bf = (best // b).astype(jnp.int32)
        bb = (best % b).astype(jnp.int32)
        dead = ~jnp.isfinite(best_gain) | (best_gain <= 0.0)
        bf = jnp.where(dead, -1, bf)

        pad = (0, max_nodes - n_nodes)
        feat_rows.append(jnp.pad(bf, pad, constant_values=-1))
        bin_rows.append(jnp.pad(bb, pad))

        # route samples: right iff bin[best_feat] > best_bin (dead -> left)
        nf = jnp.maximum(bf[node_id], 0)  # (N,)
        sample_bin = jnp.take_along_axis(binned, nf[:, None], axis=1)[:, 0]
        go_right = (bf[node_id] >= 0) & (sample_bin > bb[node_id])
        node_id = node_id * 2 + go_right.astype(jnp.int32)

    feats = jnp.stack(feat_rows)  # (depth, max_nodes)
    bins = jnp.stack(bin_rows)

    leaf_oh = jax.nn.one_hot(node_id, max_nodes, dtype=jnp.float32)  # (N, leaves)
    leaf_g = leaf_oh.T @ g
    leaf_h = leaf_oh.T @ h
    leaf = -cfg.learning_rate * leaf_g / (leaf_h + lam)
    return feats, bins, leaf, node_id


#: Diagnostics from the most recent :func:`fit` call with ``diag=True`` —
#: {"input_sharding": str, "hlo_has_all_reduce": bool}. Test hook for the
#: sharded-fit contract (VERDICT round-1 weak #3).
last_fit_diag: dict = {}


_TRAIN_CACHE: dict[BoostConfig, object] = {}


def _jitted_train(cfg: BoostConfig, use_matmul: bool):
    """jit(train) cached per (config, histogram strategy) — a fresh jit
    object per fit() would recompile the whole T-tree program on every
    call (seconds per fit)."""
    key = (cfg, use_matmul)
    fn = _TRAIN_CACHE.get(key)
    if fn is None:
        fn = jax.jit(_make_train(cfg, use_matmul))
        _TRAIN_CACHE[key] = fn
    return fn


def _make_train(cfg: BoostConfig, use_matmul: bool = True):
    """The jittable whole-fit program: (binned, y01, w) -> tree arrays.

    Under a mesh with dp-sharded inputs, the per-level histogram
    segment-sums reduce over the sharded sample axis, so GSPMD inserts the
    cross-device all-reduce (psum) for each (node, feature, bin) histogram
    — the "sharded training reductions" of BASELINE config 3. Tree arrays
    come out replicated; sample routing state stays sharded throughout.
    """

    def train(binned, y01, w):
        max_nodes = 1 << cfg.depth
        n, f = binned.shape
        # the (N, F*B) bin one-hot is invariant across trees AND levels:
        # materialize it once for the whole fit when it fits in HBM (the
        # histogram matmuls re-read it 40*depth times either way, but
        # regenerating it per level doubles the dominant HBM traffic).
        # Per-device bytes under dp sharding = total / n_shards.
        try:
            n_shards = jax.device_count()
        except Exception as e:  # noqa: BLE001
            degrade.record("boosting.device_count_probe", e, fallback="n_shards=1")
            n_shards = 1
        boh_bytes = 2 * n * f * cfg.n_bins // max(n_shards, 1)
        boh = jax.nn.one_hot(binned, cfg.n_bins, dtype=jnp.bfloat16).reshape(n, f * cfg.n_bins) \
            if use_matmul and boh_bytes <= BOH_RESIDENT_MAX_BYTES else None

        def tree_step(t, carry):
            margin, all_feats, all_bins, all_leaves = carry
            p = jax.nn.sigmoid(margin)
            g = w * (p - y01)
            h = jnp.maximum(w * p * (1.0 - p), 1e-12)
            feats, bins, leaf, node_id = _grow_tree(binned, boh, g, h, cfg, use_matmul=use_matmul)
            margin = margin + leaf[node_id]
            all_feats = jax.lax.dynamic_update_index_in_dim(all_feats, feats, t, 0)
            all_bins = jax.lax.dynamic_update_index_in_dim(all_bins, bins, t, 0)
            all_leaves = jax.lax.dynamic_update_index_in_dim(all_leaves, leaf, t, 0)
            return margin, all_feats, all_bins, all_leaves

        n = binned.shape[0]
        margin0 = jnp.full(n, cfg.base_score, dtype=jnp.float32)
        feats0 = jnp.zeros((cfg.n_trees, cfg.depth, max_nodes), dtype=jnp.int32)
        bins0 = jnp.zeros((cfg.n_trees, cfg.depth, max_nodes), dtype=jnp.int32)
        leaves0 = jnp.zeros((cfg.n_trees, max_nodes), dtype=jnp.float32)
        return jax.lax.fori_loop(0, cfg.n_trees, tree_step, (margin0, feats0, bins0, leaves0))

    return train


def fit(
    x: np.ndarray | jnp.ndarray,
    y: np.ndarray | jnp.ndarray,
    sample_weight: np.ndarray | None = None,
    cfg: BoostConfig = BoostConfig(),
    feature_names: list[str] | None = None,
    edges: np.ndarray | None = None,
    mesh=None,
    diag: bool = False,
) -> FlatForest:
    """Fit a boosted forest; the full T-tree loop runs as one jit.

    With ``mesh`` given, the sample axis is padded to the dp size, inputs
    are device_put with dp sharding (padding rows carry weight 0, so their
    gradient/hessian contributions vanish), and the WHOLE training program
    runs under the mesh — no host gather anywhere. Histogram reductions
    psum across devices; the same program runs 1-chip or on a pod.
    """
    # Keep device inputs on device (a dp-sharded x must NOT round-trip
    # through host); host inputs are converted to float32 numpy exactly once.
    def _prep(a, like=None):
        if a is None:
            a = np.ones(like.shape[0], dtype=np.float32) if isinstance(like, np.ndarray) else jnp.ones(like.shape[0], jnp.float32)
        if isinstance(a, jax.Array):
            return a.astype(jnp.float32)
        return np.asarray(a, dtype=np.float32)

    x = _prep(x)
    y01 = _prep(y)
    w = _prep(sample_weight, like=y01)
    if edges is None:
        # quantiles are host math; device inputs are gathered here by design
        # (pass `edges` for a fully on-device fit)
        with jax.transfer_guard("allow"):
            edges = quantile_bin_edges(np.asarray(x, dtype=np.float32), cfg.n_bins)
    edges_d = jnp.asarray(edges)

    # host inputs are binned on host and shipped as uint8 (4x less transfer
    # than f32 features — the dominant per-fit cost on remote devices);
    # device/sharded inputs bin on device (computation-follows-data)
    host_binned = None
    if not isinstance(x, jax.Array) and cfg.n_bins <= 256:
        from variantcalling_tpu import native

        host_binned = native.bin_features(x, np.asarray(edges, dtype=np.float32))
        if host_binned is None:
            # bin at float32 like the native kernel (and the device path's
            # f32 features): a float64 comparison against an edge could
            # land a borderline value one bin off depending on which path
            # happened to run
            x32 = np.asarray(x, dtype=np.float32)
            e32 = np.asarray(edges, dtype=np.float32)
            host_binned = np.empty(x.shape, dtype=np.uint8)
            for j in range(x.shape[1]):
                host_binned[:, j] = np.searchsorted(e32[j], x32[:, j])

    # histogram strategy follows the devices the fit actually runs on
    # (mesh > device input > default device), not the process default
    try:
        if mesh is not None:
            platform = mesh.devices.flat[0].platform
        elif isinstance(x, jax.Array):
            platform = next(iter(x.devices())).platform
        else:
            platform = jax.devices()[0].platform
    except Exception as e:  # noqa: BLE001 — device probe must not break the fit
        degrade.record("boosting.platform_probe", e, fallback="platform=cpu")
        platform = "cpu"

    # CPU fallback with host inputs: the native partitioned-sample trainer
    # (sibling-subtraction histograms, native/src/vctpu_gbt.cc) beats XLA's
    # generic scatter ~5x on one core; same binning, gain formula, and
    # output layout as the jitted program. Checked BEFORE any device
    # placement so the fallback pays zero XLA transfers. Mesh / device-
    # resident fits stay on the jitted path (that's the TPU/pod program).
    # caller-supplied edges must agree with cfg.n_bins (bin ids reach
    # edges.shape[1], and the native kernel indexes histograms by them)
    if platform == "cpu" and mesh is None and host_binned is not None and not diag \
            and np.asarray(edges).shape[1] == cfg.n_bins - 1 \
            and knobs.get_bool("VCTPU_NATIVE_GBT"):
        from variantcalling_tpu import native

        w_arr = None if sample_weight is None else np.asarray(w, dtype=np.float32)
        res = native.gbt_fit(host_binned, np.asarray(y01), w_arr,
                             cfg.n_trees, cfg.depth, cfg.n_bins,
                             cfg.learning_rate, cfg.reg_lambda,
                             cfg.min_child_weight, cfg.base_score)
        if res is not None:
            feats_n, bins_n, leaves_n = res
            return _to_flat_forest(feats_n, bins_n, leaves_n,
                                   np.asarray(edges), cfg, feature_names)

    if mesh is not None:
        from variantcalling_tpu.parallel.mesh import DATA_AXIS, data_sharding, pad_to_multiple

        n_dp = mesh.shape[DATA_AXIS]
        n = x.shape[0]
        target = ((n + n_dp - 1) // n_dp) * n_dp

        def _pad_put(a, ndim):
            if isinstance(a, jax.Array):
                widths = ((0, target - n),) + ((0, 0),) * (ndim - 1)
                padded = jnp.pad(a, widths)  # fill=0 -> padding rows weightless
            else:
                padded, _ = pad_to_multiple(a, n_dp)
            return jax.device_put(padded, data_sharding(mesh, ndim))

        yd, wd = _pad_put(y01, 1), _pad_put(w, 1)
        binned = _pad_put(host_binned, 2) if host_binned is not None else \
            bin_features(_pad_put(x, 2), edges_d)
    else:
        yd = y01 if isinstance(y01, jax.Array) else jnp.asarray(y01)
        wd = w if isinstance(w, jax.Array) else jnp.asarray(w)
        binned = jnp.asarray(host_binned) if host_binned is not None else \
            bin_features(x if isinstance(x, jax.Array) else jnp.asarray(x), edges_d)

    train = _jitted_train(cfg, use_matmul=platform != "cpu")
    ctx = mesh if mesh is not None else nullcontext()
    with ctx:
        if diag:
            lowered = train.lower(binned, yd, wd)
            compiled = lowered.compile()
            hlo = compiled.as_text()
            last_fit_diag.clear()
            last_fit_diag.update(
                input_sharding=str(getattr(binned.sharding, "spec", binned.sharding)),
                hlo_has_all_reduce="all-reduce" in hlo,
            )
            _, all_feats, all_bins, all_leaves = compiled(binned, yd, wd)
        else:
            _, all_feats, all_bins, all_leaves = train(binned, yd, wd)
    with jax.transfer_guard("allow"):  # outputs are host arrays by contract
        return _to_flat_forest(
            np.asarray(all_feats), np.asarray(all_bins), np.asarray(all_leaves), np.asarray(edges), cfg, feature_names
        )


def _to_flat_forest(
    feats: np.ndarray,  # (T, D, 2^D)
    bins: np.ndarray,
    leaves: np.ndarray,  # (T, 2^D)
    edges: np.ndarray,  # (F, B-1)
    cfg: BoostConfig,
    feature_names: list[str] | None,
) -> FlatForest:
    """Heap-layout complete trees -> FlatForest node arrays.

    Internal node (level l, k-th) sits at heap index 2^l-1+k; leaves fill
    the last level. Dead splits keep feature 0 with threshold +inf (all
    samples route left), preserving the complete-tree shape.
    """
    t, d, _ = feats.shape
    n_leaves = 1 << d
    m = (1 << (d + 1)) - 1
    feature = np.full((t, m), LEAF, dtype=np.int32)
    threshold = np.zeros((t, m), dtype=np.float32)
    left = np.tile(np.arange(m, dtype=np.int32), (t, 1))
    right = np.tile(np.arange(m, dtype=np.int32), (t, 1))
    value = np.zeros((t, m), dtype=np.float32)

    b = cfg.n_bins
    for level in range(d):
        n_nodes = 1 << level
        base = (1 << level) - 1
        idx = base + np.arange(n_nodes)
        bf = feats[:, level, :n_nodes]  # (T, n_nodes)
        bb = bins[:, level, :n_nodes]
        dead = bf < 0
        safe_f = np.maximum(bf, 0)
        # split "bin <= j" -> threshold edges[f, j] (right-open); last edge
        # index clamped (no-split guards make it unreachable)
        thr = edges[safe_f, np.minimum(bb, edges.shape[1] - 1)]
        feature[:, idx] = np.where(dead, 0, safe_f)
        threshold[:, idx] = np.where(dead, np.float32(np.inf), thr)
        left[:, idx] = 2 * idx + 1
        right[:, idx] = 2 * idx + 2
    leaf_idx = (1 << d) - 1 + np.arange(n_leaves)
    value[:, leaf_idx] = leaves
    return FlatForest(
        feature=feature,
        threshold=threshold,
        left=left,
        right=right,
        value=value,
        max_depth=d,
        aggregation="logit_sum",
        base_score=cfg.base_score,
        feature_names=feature_names or [],
    )
