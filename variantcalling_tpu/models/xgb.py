"""xgboost model ingestion -> FlatForest (TPU inference for the reference's
production classifiers).

The reference's filtering models are xgboost 2.1.2 artifacts
(setup/environment.yml:451, docs/howto-callset-filter.md:114); SURVEY §2.5
names faithful forest-pickle loading a core replacement target. This module
ingests them WITHOUT requiring the xgboost library: the ≥1.6 JSON model
format (``Booster.save_model("*.json")``) is parsed directly, and live
``Booster``/``XGBClassifier`` objects round-trip through that same dump
when xgboost happens to be importable.

Semantics mapped exactly onto the FlatForest traversal:

- xgboost splits are ``x < split_condition`` -> left, while FlatForest
  walks ``x <= threshold`` -> left. For float32 operands the two are
  identical under ``threshold = nextafter(split_condition, -inf)``.
- missing values (NaN) take the node's ``default_left`` branch — carried
  as FlatForest.default_left and honored by both the gather-walk and GEMM
  predictors.
- leaf values in the dump already include the learning rate; the margin
  sum passes through sigmoid with ``base_score`` mapped through the
  objective's prob->margin transform (logit for binary:logistic).
"""

from __future__ import annotations

import json
import math

import numpy as np

from variantcalling_tpu.models.forest import LEAF, FlatForest

_LOGISTIC_OBJECTIVES = {"binary:logistic", "reg:logistic"}


def _lt_to_le(cond: np.ndarray) -> np.ndarray:
    """Largest float32 strictly below each split condition: makes
    ``x <= thr`` decide exactly like xgboost's ``x < cond`` for f32 x."""
    c = cond.astype(np.float32)
    return np.nextafter(c, np.float32(-np.inf)).astype(np.float32)


def from_xgboost_json(source, feature_names: list[str] | None = None,
                      pass_threshold: float = 0.5) -> FlatForest:
    """Parse an xgboost JSON model (path, JSON string, or parsed dict).

    Binary classification only (``num_class`` 0/2 with a logistic
    objective) — the reference's filtering models are all binary
    TP-vs-FP classifiers.
    """
    if isinstance(source, (str, bytes, bytearray)):
        s = source if isinstance(source, str) else bytes(source).decode()
        if s.lstrip().startswith("{"):
            obj = json.loads(s)
        else:
            with open(s) as fh:
                obj = json.load(fh)
    else:
        obj = source
    learner = obj["learner"]

    booster_name = learner["gradient_booster"].get("name", "gbtree")
    if booster_name == "dart":
        raise ValueError("dart boosters (per-tree drop weights) are not supported")
    num_class = int(learner["learner_model_param"].get("num_class", "0") or 0)
    if num_class not in (0, 1, 2):
        raise ValueError(f"only binary models are supported (num_class={num_class})")
    objective = learner.get("objective", {}).get("name", "binary:logistic")
    if objective not in _LOGISTIC_OBJECTIVES:
        raise ValueError(f"only logistic objectives are supported (got {objective!r})")
    if num_class == 2:
        # binary logistic stores num_class=0; an actual 2-class softprob
        # model carries one tree set per class and does not sum-then-sigmoid
        raise ValueError("multi:softprob with num_class=2 is not supported; "
                         "retrain with binary:logistic")

    base_prob = float(learner["learner_model_param"].get("base_score", "0.5") or 0.5)
    base_prob = min(max(base_prob, 1e-12), 1 - 1e-12)
    base_margin = math.log(base_prob / (1.0 - base_prob))

    trees = learner["gradient_booster"]["model"]["trees"]
    if not trees:
        raise ValueError("model contains no trees")
    n_nodes = [len(t["left_children"]) for t in trees]
    m = max(n_nodes)
    t_n = len(trees)
    feature = np.full((t_n, m), LEAF, dtype=np.int32)
    threshold = np.zeros((t_n, m), dtype=np.float32)
    left = np.zeros((t_n, m), dtype=np.int32)
    right = np.zeros((t_n, m), dtype=np.int32)
    value = np.zeros((t_n, m), dtype=np.float32)
    default_left = np.zeros((t_n, m), dtype=bool)
    max_depth = 1
    for ti, tree in enumerate(trees):
        if tree.get("categories_nodes"):
            raise ValueError("categorical splits are not supported")
        lc = np.asarray(tree["left_children"], dtype=np.int32)
        rc = np.asarray(tree["right_children"], dtype=np.int32)
        cond = np.asarray(tree["split_conditions"], dtype=np.float32)
        sidx = np.asarray(tree["split_indices"], dtype=np.int32)
        dl = np.asarray(tree["default_left"], dtype=bool)
        nc = len(lc)
        is_leaf = lc == -1
        node_ids = np.arange(nc, dtype=np.int32)
        feature[ti, :nc] = np.where(is_leaf, LEAF, sidx)
        threshold[ti, :nc] = np.where(is_leaf, 0.0, _lt_to_le(cond))
        left[ti, :nc] = np.where(is_leaf, node_ids, lc)
        right[ti, :nc] = np.where(is_leaf, node_ids, rc)
        # for leaves, split_conditions holds the leaf value (eta included)
        value[ti, :nc] = np.where(is_leaf, cond, 0.0)
        default_left[ti, :nc] = ~is_leaf & dl
        # tree_param.depth is optional; derive from the child arrays by
        # BFS from the root. A plain id-order pass would assume children
        # have larger ids than their parent, but pruned models
        # (tree_param.num_deleted > 0) recycle node ids, so a child can
        # precede its parent — underestimating depth and truncating the
        # fixed-round traversal at an internal node
        depth = np.zeros(nc, dtype=np.int32)
        frontier = [0]
        level = 0
        while frontier:
            level += 1
            if level > nc:  # a tree of nc nodes has < nc levels
                raise ValueError("malformed model: cyclic child pointers")
            nxt = set()
            for node in frontier:
                if not is_leaf[node]:
                    depth[lc[node]] = depth[node] + 1
                    depth[rc[node]] = depth[node] + 1
                    nxt.add(int(lc[node]))
                    nxt.add(int(rc[node]))
            # dedup bounds the frontier at nc, so converging/cyclic child
            # pointers hit the level guard instead of growing the frontier
            frontier = sorted(nxt)
        max_depth = max(max_depth, int(depth.max()) + 1)

    names = feature_names
    if names is None:
        names = list(learner.get("feature_names") or [])
    return FlatForest(
        feature=feature,
        threshold=threshold,
        left=left,
        right=right,
        value=value,
        max_depth=max_depth,
        aggregation="logit_sum",
        base_score=base_margin,
        feature_names=names or [],
        pass_threshold=pass_threshold,
        default_left=default_left,
    )


def from_xgboost(model, feature_names: list[str] | None = None,
                 pass_threshold: float = 0.5) -> FlatForest:
    """Convert a live Booster / XGBClassifier via its own JSON dump
    (requires xgboost importable — only the case when the pickle that
    carried the model could itself be loaded)."""
    booster = model.get_booster() if hasattr(model, "get_booster") else model
    if feature_names is None:
        fni = getattr(model, "feature_names_in_", None)
        if fni is not None:
            feature_names = list(fni)
    raw = booster.save_raw(raw_format="json")
    return from_xgboost_json(raw, feature_names=feature_names,
                             pass_threshold=pass_threshold)


def looks_like_xgboost(model) -> bool:
    return type(model).__module__.split(".")[0] == "xgboost"
