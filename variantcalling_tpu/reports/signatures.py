"""Mutational-signature fitting and extraction as device kernels.

The reference assigns somatic mutational signatures by subprocessing
SigProfilerAssignment/SigProfilerMatrixGenerator (run_no_gt_report.py:
334-595) and annotates results from a COSMIC metadata json
(test resource somatic_test.cosmic_signatures_v3.3.json — descriptions/
links only; the 96-channel definitions ship separately as a COSMIC tsv).
This module replaces the external fitters with JAX kernels:

- :func:`fit_signatures` — known-signature assignment: non-negative
  least squares on the 96-channel SBS counts via multiplicative updates
  (Lee–Seung, KL objective — the same family SigProfiler uses), jitted,
  batched over samples.
- :func:`extract_signatures` — de-novo extraction: KL-NMF with
  multiplicative updates over a (samples, 96) matrix.
- :func:`cosine_similarity_matrix` — match extracted signatures to a
  reference catalog.
"""

from __future__ import annotations

import json

import numpy as np
import pandas as pd

import jax
import jax.numpy as jnp

_EPS = 1e-12


def load_signature_matrix(path: str) -> pd.DataFrame:
    """COSMIC-style signature definitions: rows = 96 contexts, cols = signatures.

    Accepts the COSMIC tsv/csv layout (first column 'Type' like 'A[C>A]A')."""
    sep = "\t" if path.endswith((".tsv", ".txt")) else ","
    df = pd.read_csv(path, sep=sep)
    df = df.set_index(df.columns[0])
    return df


def load_signature_metadata(path: str) -> dict[str, dict]:
    """The reference's cosmic_signatures json: {SBS1: {description, link}}."""
    with open(path) as fh:
        return json.load(fh)


@jax.jit
def _nnls_kl_updates(exposures: jnp.ndarray, sigs: jnp.ndarray, counts: jnp.ndarray) -> jnp.ndarray:
    """One multiplicative KL update: e <- e * (S^T (c / (S e))) / (S^T 1)."""
    recon = sigs @ exposures + _EPS  # (96,) per sample via vmap
    ratio = counts / recon
    num = sigs.T @ ratio
    den = jnp.sum(sigs, axis=0) + _EPS
    return exposures * num / den


def fit_signatures(
    counts: np.ndarray, signatures: np.ndarray, n_iter: int = 500
) -> np.ndarray:
    """Exposures (S, K) explaining counts (S, 96) with signatures (96, K).

    Batched over samples with vmap; the whole iteration runs as one jitted
    lax.fori_loop on device.
    """
    counts = jnp.asarray(np.atleast_2d(counts), dtype=jnp.float32)
    sigs = jnp.asarray(signatures, dtype=jnp.float32)
    sigs = sigs / jnp.maximum(sigs.sum(axis=0, keepdims=True), _EPS)  # column-stochastic
    k = sigs.shape[1]

    def fit_one(c):
        e0 = jnp.full((k,), jnp.maximum(c.sum(), 1.0) / k, dtype=jnp.float32)
        return jax.lax.fori_loop(0, n_iter, lambda _, e: _nnls_kl_updates(e, sigs, c), e0)

    out = jax.vmap(fit_one)(counts)
    return np.asarray(out)


def sparsify_exposures(exposures: np.ndarray, min_fraction: float = 0.03) -> np.ndarray:
    """Zero signatures contributing < min_fraction of a sample's mutations
    (SigProfilerAssignment's sparsity heuristic)."""
    total = exposures.sum(axis=1, keepdims=True)
    frac = exposures / np.maximum(total, _EPS)
    return np.where(frac >= min_fraction, exposures, 0.0)


def extract_signatures(
    counts: np.ndarray, n_signatures: int, n_iter: int = 2000, seed: int = 0
) -> tuple[np.ndarray, np.ndarray]:
    """De-novo KL-NMF: counts (S, 96) ~= exposures (S, K) @ sigs.T (K, 96).

    Returns (signatures (96, K) column-normalized, exposures (S, K)).
    """
    c = jnp.asarray(np.atleast_2d(counts), dtype=jnp.float32).T  # (96, S)
    key = jax.random.PRNGKey(seed)
    k1, k2 = jax.random.split(key)
    w0 = jax.random.uniform(k1, (c.shape[0], n_signatures), minval=0.1, maxval=1.0)
    h0 = jax.random.uniform(k2, (n_signatures, c.shape[1]), minval=0.1, maxval=1.0)

    def step(_, wh):
        w, h = wh
        recon = w @ h + _EPS
        h = h * (w.T @ (c / recon)) / (jnp.sum(w, axis=0)[:, None] + _EPS)
        recon = w @ h + _EPS
        w = w * ((c / recon) @ h.T) / (jnp.sum(h, axis=1)[None, :] + _EPS)
        return w, h

    w, h = jax.lax.fori_loop(0, n_iter, step, (w0, h0))
    w = np.asarray(w)
    h = np.asarray(h)
    scale = w.sum(axis=0)
    w = w / np.maximum(scale, _EPS)
    h = h * scale[:, None]
    return w, h.T


def cosine_similarity_matrix(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """(Ka, Kb) cosine similarities between signature columns."""
    an = a / np.maximum(np.linalg.norm(a, axis=0, keepdims=True), _EPS)
    bn = b / np.maximum(np.linalg.norm(b, axis=0, keepdims=True), _EPS)
    return an.T @ bn


def assignment_table(
    exposures: np.ndarray,
    signature_names: list[str],
    metadata: dict[str, dict] | None = None,
    sample_names: list[str] | None = None,
) -> pd.DataFrame:
    """Long-form exposures with optional COSMIC metadata annotation."""
    exposures = np.atleast_2d(exposures)
    samples = sample_names or [f"sample{i}" for i in range(exposures.shape[0])]
    rows = []
    for si, sample in enumerate(samples):
        total = exposures[si].sum()
        for ki, name in enumerate(signature_names):
            if exposures[si, ki] <= 0:
                continue
            row = {
                "sample": sample,
                "signature": name,
                "mutations": float(exposures[si, ki]),
                "fraction": float(exposures[si, ki] / max(total, _EPS)),
            }
            if metadata and name in metadata:
                row["description"] = metadata[name].get(
                    "description", metadata[name].get("descprition", "")
                )
            rows.append(row)
    return pd.DataFrame(rows).sort_values(["sample", "mutations"], ascending=[True, False]).reset_index(drop=True)
