"""Mutational-signature fitting and extraction as device kernels.

The reference assigns somatic mutational signatures by subprocessing
SigProfilerAssignment/SigProfilerMatrixGenerator (run_no_gt_report.py:
334-595) and annotates results from a COSMIC metadata json
(test resource somatic_test.cosmic_signatures_v3.3.json — descriptions/
links only; the 96-channel definitions ship separately as a COSMIC tsv).
This module replaces the external fitters with JAX kernels:

- :func:`fit_signatures` — known-signature assignment: non-negative
  least squares on the 96-channel SBS counts via multiplicative updates
  (Lee–Seung, KL objective — the same family SigProfiler uses), jitted,
  batched over samples.
- :func:`extract_signatures` — de-novo extraction: KL-NMF with
  multiplicative updates over a (samples, 96) matrix.
- :func:`cosine_similarity_matrix` — match extracted signatures to a
  reference catalog.
"""

from __future__ import annotations

import json

import numpy as np
import pandas as pd

import jax
import jax.numpy as jnp

_EPS = 1e-12


def load_signature_matrix(path: str) -> pd.DataFrame:
    """COSMIC-style signature definitions: rows = 96 contexts, cols = signatures.

    Accepts the COSMIC tsv/csv layout (first column 'Type' like 'A[C>A]A')."""
    sep = "\t" if path.endswith((".tsv", ".txt")) else ","
    df = pd.read_csv(path, sep=sep)
    df = df.set_index(df.columns[0])
    return df


def load_signature_metadata(path: str) -> dict[str, dict]:
    """The reference's cosmic_signatures json: {SBS1: {description, link}}."""
    with open(path) as fh:
        return json.load(fh)


@jax.jit
def _nnls_kl_updates(exposures: jnp.ndarray, sigs: jnp.ndarray, counts: jnp.ndarray) -> jnp.ndarray:
    """One multiplicative KL update: e <- e * (S^T (c / (S e))) / (S^T 1)."""
    recon = sigs @ exposures + _EPS  # (96,) per sample via vmap
    ratio = counts / recon
    num = sigs.T @ ratio
    den = jnp.sum(sigs, axis=0) + _EPS
    return exposures * num / den


def fit_signatures(
    counts: np.ndarray, signatures: np.ndarray, n_iter: int = 500
) -> np.ndarray:
    """Exposures (S, K) explaining counts (S, 96) with signatures (96, K).

    Batched over samples with vmap; the whole iteration runs as one jitted
    lax.fori_loop on device.
    """
    counts = jnp.asarray(np.atleast_2d(counts), dtype=jnp.float32)
    sigs = jnp.asarray(signatures, dtype=jnp.float32)
    sigs = sigs / jnp.maximum(sigs.sum(axis=0, keepdims=True), _EPS)  # column-stochastic
    k = sigs.shape[1]

    def fit_one(c):
        e0 = jnp.full((k,), jnp.maximum(c.sum(), 1.0) / k, dtype=jnp.float32)
        return jax.lax.fori_loop(0, n_iter, lambda _, e: _nnls_kl_updates(e, sigs, c), e0)

    out = jax.vmap(fit_one)(counts)
    return np.asarray(out)


def sparsify_exposures(exposures: np.ndarray, min_fraction: float = 0.03) -> np.ndarray:
    """Zero signatures contributing < min_fraction of a sample's mutations
    (SigProfilerAssignment's sparsity heuristic)."""
    total = exposures.sum(axis=1, keepdims=True)
    frac = exposures / np.maximum(total, _EPS)
    return np.where(frac >= min_fraction, exposures, 0.0)


def extract_signatures(
    counts: np.ndarray, n_signatures: int, n_iter: int = 2000, seed: int = 0
) -> tuple[np.ndarray, np.ndarray]:
    """De-novo KL-NMF: counts (S, 96) ~= exposures (S, K) @ sigs.T (K, 96).

    Returns (signatures (96, K) column-normalized, exposures (S, K)).
    """
    c = jnp.asarray(np.atleast_2d(counts), dtype=jnp.float32).T  # (96, S)
    key = jax.random.PRNGKey(seed)
    k1, k2 = jax.random.split(key)
    w0 = jax.random.uniform(k1, (c.shape[0], n_signatures), minval=0.1, maxval=1.0)
    h0 = jax.random.uniform(k2, (n_signatures, c.shape[1]), minval=0.1, maxval=1.0)

    def step(_, wh):
        w, h = wh
        recon = w @ h + _EPS
        h = h * (w.T @ (c / recon)) / (jnp.sum(w, axis=0)[:, None] + _EPS)
        recon = w @ h + _EPS
        w = w * ((c / recon) @ h.T) / (jnp.sum(h, axis=1)[None, :] + _EPS)
        return w, h

    w, h = jax.lax.fori_loop(0, n_iter, step, (w0, h0))
    w = np.asarray(w)
    h = np.asarray(h)
    scale = w.sum(axis=0)
    w = w / np.maximum(scale, _EPS)
    h = h * scale[:, None]
    return w, h.T


_COMP = {"A": "T", "C": "G", "G": "C", "T": "A"}


def _rc(s: str) -> str:
    return "".join(_COMP[c] for c in reversed(s))


def id83_labels() -> list[str]:
    """The 83 COSMIC indel channels, SigProfiler label layout
    ``{len}:{Del|Ins}:{C|T|R|M}:{n}`` (len 5 means 5+, n 5 means 5+)."""
    labels = []
    for kind in ("Del", "Ins"):
        for base in ("C", "T"):
            labels += [f"1:{kind}:{base}:{n}" for n in range(6)]
    for kind in ("Del", "Ins"):
        for ln in (2, 3, 4, 5):
            labels += [f"{ln}:{kind}:R:{n}" for n in range(6)]
    for ln, max_mh in ((2, 1), (3, 2), (4, 3), (5, 5)):
        labels += [f"{ln}:Del:M:{m}" for m in range(1, max_mh + 1)]
    assert len(labels) == 83
    return labels


def _repeat_count(seq: str, unit: str) -> int:
    """Copies of ``unit`` at the start of ``seq``."""
    n = 0
    u = len(unit)
    while seq[n * u : (n + 1) * u] == unit:
        n += 1
    return n


def classify_indel_id83(ref: str, alt: str, right_ctx: str, left_ctx: str) -> str | None:
    """COSMIC ID83 channel for a left-anchored simple indel, or None.

    ``right_ctx`` is the reference sequence immediately AFTER the record's
    REF span; ``left_ctx`` ends AT (and includes) the anchor base at POS —
    the deleted segment's true left neighbor. Classification follows
    the SigProfilerMatrixGenerator scheme (reference run_no_gt_report.py:
    334-595 delegates to it): 1-bp indels bucket by pyrimidine-folded base
    and adjacent homopolymer run; longer indels by repeat count of the
    unit; repeat-free deletions by microhomology with the flanks.
    """
    if len(ref) == len(alt) or not ref or not alt or ref[0] != alt[0]:
        return None
    if len(ref) > 1 and len(alt) > 1:
        return None  # complex substitution, not a simple indel
    kind = "Del" if len(ref) > len(alt) else "Ins"
    unit = (ref if kind == "Del" else alt)[1:]
    if not unit or any(c not in "ACGT" for c in unit):
        return None
    ln = len(unit)
    lb = min(ln, 5)
    # reference sequence following the indel site: for a deletion the
    # context after the deleted copy; for an insertion right after POS
    following = right_ctx
    if ln == 1:
        base = unit if unit in ("C", "T") else _COMP[unit]
        # additional copies of the base adjacent in the reference
        n = min(_repeat_count(following, unit), 5)
        return f"1:{kind}:{base}:{n}"
    n = min(_repeat_count(following, unit), 5)
    if kind == "Del" and n == 0:
        # microhomology: shared prefix with the right flank or shared
        # suffix with the left flank
        mh_r = 0
        while mh_r < ln - 1 and mh_r < len(following) and unit[mh_r] == following[mh_r]:
            mh_r += 1
        mh_l = 0
        while (mh_l < ln - 1 and mh_l < len(left_ctx)
               and unit[ln - 1 - mh_l] == left_ctx[len(left_ctx) - 1 - mh_l]):
            mh_l += 1
        mh = max(mh_r, mh_l)
        if mh > 0:
            max_mh = {2: 1, 3: 2, 4: 3, 5: 5}[lb]
            return f"{lb}:Del:M:{min(mh, max_mh)}"
    return f"{lb}:{kind}:R:{n}"


def id83_matrix(records, fasta) -> pd.Series:
    """83-channel indel counts for an iterable of (chrom, pos, ref, alt).

    ``pos`` is 1-based (VCF); reference context comes from ``fasta``."""
    labels = id83_labels()
    idx = {l: i for i, l in enumerate(labels)}
    counts = np.zeros(83, dtype=np.int64)
    for chrom, pos, ref, alt in records:
        if chrom not in fasta.references:
            continue
        end = pos - 1 + len(ref)
        right = fasta.fetch(chrom, end, end + 6 * max(len(ref), len(alt)))
        # left flank INCLUDES the anchor base (the deleted segment starts
        # right after it) — excluding it compared microhomology against
        # sequence one base removed from the deletion
        left = fasta.fetch(chrom, max(0, pos - 1 - 6), pos)
        ch = classify_indel_id83(ref, alt, right.upper(), left.upper())
        if ch is not None:
            counts[idx[ch]] += 1
    return pd.Series(counts, index=labels, name="size")


_DBS_CANON_REFS = ("AC", "AT", "CC", "CG", "CT", "GC", "TA", "TC", "TG", "TT")


def dbs78_labels() -> list[str]:
    """The 78 COSMIC doublet channels ('AC>CA' style): canonical ref
    doublets with revcomp folding; palindromic refs (AT/CG/GC/TA) fold
    the alt to the lexicographic minimum of (alt, revcomp(alt))."""
    out = []
    for ref in _DBS_CANON_REFS:
        seen = set()
        for a0 in "ACGT":
            for a1 in "ACGT":
                if a0 == ref[0] or a1 == ref[1]:
                    continue
                alt = a0 + a1
                if _rc(ref) == ref:
                    alt = min(alt, _rc(alt))
                if alt not in seen:
                    seen.add(alt)
                    out.append(f"{ref}>{alt}")
    assert len(out) == 78
    return out


def classify_doublet_dbs78(ref: str, alt: str) -> str | None:
    """Canonical DBS78 channel for a 2-bp REF/ALT pair, or None."""
    if len(ref) != 2 or len(alt) != 2 or ref == alt:
        return None
    if any(c not in "ACGT" for c in ref + alt):
        return None
    if alt[0] == ref[0] or alt[1] == ref[1]:
        return None  # not a true doublet substitution at both positions
    if ref not in _DBS_CANON_REFS:  # exactly one of {ref, rc(ref)} is canonical
        ref, alt = _rc(ref), _rc(alt)
        if ref not in _DBS_CANON_REFS:
            return None
    if _rc(ref) == ref:
        alt = min(alt, _rc(alt))
    return f"{ref}>{alt}"


def dbs78_matrix(table, return_paired: bool = False):
    """78-channel doublet counts from a VariantTable: explicit 2-bp MNP
    records plus ADJACENT SNV pairs merged into doublets (the
    SigProfilerMatrixGenerator convention). Runs of >=3 consecutive SNVs
    are multi-base substitutions under that convention — they enter
    NEITHER catalog (no greedy doublet + leftover-SBS split).

    ``return_paired=True`` additionally returns the boolean mask of SNV
    records consumed as doublet halves or longer-MNV members — callers
    exclude them from the SBS96 matrix so each mutation is counted in
    exactly one catalog (or none, for >=3-runs)."""
    labels = dbs78_labels()
    idx = {l: i for i, l in enumerate(labels)}
    counts = np.zeros(78, dtype=np.int64)
    chrom = np.asarray(table.chrom)
    pos = np.asarray(table.pos)
    refs = np.asarray(table.ref)
    alts = np.asarray(table.alt)
    n = len(pos)
    is_snv = np.zeros(n, dtype=bool)
    for i in range(n):
        r, a = refs[i], alts[i].split(",")[0]
        if len(r) == 2 and len(a) == 2:
            ch = classify_doublet_dbs78(r.upper(), a.upper())
            if ch is not None:
                counts[idx[ch]] += 1
        elif len(r) == 1 and len(a) == 1 and r in "ACGT" and a in "ACGT":
            is_snv[i] = True
    # maximal runs of adjacent SNVs (sorted input): length 2 -> doublet,
    # length >=3 -> multi-base substitution, excluded from both catalogs
    paired = np.zeros(n, dtype=bool)
    i = 0
    while i < n:
        if not is_snv[i]:
            i += 1
            continue
        j = i
        while (j + 1 < n and is_snv[j + 1] and chrom[j + 1] == chrom[j]
               and int(pos[j + 1]) == int(pos[j]) + 1):
            j += 1
        run = j - i + 1
        if run == 2:
            ch = classify_doublet_dbs78(
                (refs[i] + refs[i + 1]).upper(),
                (alts[i].split(",")[0] + alts[i + 1].split(",")[0]).upper())
            if ch is not None:
                counts[idx[ch]] += 1
                paired[i] = paired[i + 1] = True
        elif run >= 3:
            paired[i : j + 1] = True  # consumed by the MNV, counted nowhere
        i = j + 1
    series = pd.Series(counts, index=labels, name="size")
    return (series, paired) if return_paired else series


def cosine_similarity_matrix(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """(Ka, Kb) cosine similarities between signature columns."""
    an = a / np.maximum(np.linalg.norm(a, axis=0, keepdims=True), _EPS)
    bn = b / np.maximum(np.linalg.norm(b, axis=0, keepdims=True), _EPS)
    return an.T @ bn


def assignment_table(
    exposures: np.ndarray,
    signature_names: list[str],
    metadata: dict[str, dict] | None = None,
    sample_names: list[str] | None = None,
) -> pd.DataFrame:
    """Long-form exposures with optional COSMIC metadata annotation."""
    exposures = np.atleast_2d(exposures)
    samples = sample_names or [f"sample{i}" for i in range(exposures.shape[0])]
    rows = []
    for si, sample in enumerate(samples):
        total = exposures[si].sum()
        for ki, name in enumerate(signature_names):
            if exposures[si, ki] <= 0:
                continue
            row = {
                "sample": sample,
                "signature": name,
                "mutations": float(exposures[si, ki]),
                "fraction": float(exposures[si, ki] / max(total, _EPS)),
            }
            if metadata and name in metadata:
                row["description"] = metadata[name].get(
                    "description", metadata[name].get("descprition", "")
                )
            rows.append(row)
    return pd.DataFrame(rows).sort_values(["sample", "mutations"], ascending=[True, False]).reset_index(drop=True)
