"""Report subsystem: metric tables, data loaders, and report generation.

TPU-native counterpart of ``ugvc/reports`` + the GATK VariantEval tables
the reference parses from subprocess output (run_no_gt_report.py:175-256).
All tables here are computed in-process from columnar variant tables with
batched device reductions.
"""
