"""Minimal self-contained HTML report writer (shared by the report tools).

The reference renders reports by papermill-executing notebooks and
nbconvert-ing to HTML (test_vc_report.py:15-26). These generators emit the
same artifact — titled sections of tables and inline images — without a
notebook runtime.
"""

from __future__ import annotations

import base64
import html as _html
import io

import pandas as pd

_STYLE = """
body { font-family: -apple-system, Segoe UI, sans-serif; margin: 2em; color: #222; }
h1 { border-bottom: 2px solid #444; padding-bottom: .2em; }
h2 { margin-top: 1.6em; color: #333; }
table { border-collapse: collapse; margin: .8em 0; font-size: .92em; }
th, td { border: 1px solid #bbb; padding: .3em .7em; text-align: right; }
th { background: #f0f0f0; }
td:first-child, th:first-child { text-align: left; }
img { max-width: 100%; }
.param { color: #666; font-size: .9em; }
"""


class HtmlReport:
    def __init__(self, title: str):
        self.title = title
        self.parts: list[str] = []

    def add_params(self, params: dict) -> None:
        rows = "".join(
            f"<tr><td>{_html.escape(str(k))}</td><td>{_html.escape(str(v))}</td></tr>"
            for k, v in params.items()
        )
        self.parts.append(f'<table class="param"><tr><th>parameter</th><th>value</th></tr>{rows}</table>')

    def add_section(self, heading: str) -> None:
        self.parts.append(f"<h2>{_html.escape(heading)}</h2>")

    def add_table(self, df: pd.DataFrame, float_fmt: str = "{:,.4g}") -> None:
        self.parts.append(df.to_html(float_format=lambda x: float_fmt.format(x), border=0))

    def add_text(self, text: str) -> None:
        self.parts.append(f"<p>{_html.escape(text)}</p>")

    def add_figure(self, fig) -> None:
        buf = io.BytesIO()
        fig.savefig(buf, format="png", bbox_inches="tight", dpi=110)
        b64 = base64.b64encode(buf.getvalue()).decode()
        self.parts.append(f'<img src="data:image/png;base64,{b64}"/>')

    def write(self, path: str) -> str:
        doc = (
            f"<html><head><meta charset='utf-8'><title>{_html.escape(self.title)}</title>"
            f"<style>{_STYLE}</style></head><body><h1>{_html.escape(self.title)}</h1>"
            + "".join(self.parts)
            + "</body></html>"
        )
        with open(path, "w") as fh:
            fh.write(doc)
        return path


def add_figure_safe(rep: HtmlReport, build, what: str = "figure") -> None:
    """Build a matplotlib figure (Agg), embed it, close it; never raise.

    ``build(plt)`` returns the figure (or None to skip). One home for the
    backend selection + warn-on-failure pattern the report pipelines share.
    """
    from variantcalling_tpu import logger
    from variantcalling_tpu.utils import degrade

    try:
        import matplotlib

        matplotlib.use("Agg")
        import matplotlib.pyplot as plt

        fig = build(plt)
        if fig is not None:
            rep.add_figure(fig)
            plt.close(fig)
    except Exception as e:  # noqa: BLE001 — figures are presentation only
        degrade.record("reports.figure", e, fallback=f"{what} skipped")
        logger.warning("%s skipped: %s", what, e)
