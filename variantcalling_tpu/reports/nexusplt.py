"""Figure saver: png / html / json export (reference ugvc/reports/nexusplt.py:41-89).

The reference saves matplotlib figures as png, mpld3 html, and mpld3 json.
mpld3 is not in this image, so html embeds the png (self-contained report
fragment) and json serializes the axes data (lines/labels/limits) — enough
for downstream dashboards to re-plot.
"""

from __future__ import annotations

import base64
import io
import json
import os


def save(fig, name: str, outdir: str = ".", formats: tuple[str, ...] = ("png",)) -> list[str]:
    """Save a matplotlib figure under each format; returns written paths."""
    os.makedirs(outdir, exist_ok=True)
    written = []
    for fmt in formats:
        path = os.path.join(outdir, f"{name}.{fmt}")
        if fmt == "png":
            fig.savefig(path, format="png", bbox_inches="tight", dpi=120)
        elif fmt == "html":
            buf = io.BytesIO()
            fig.savefig(buf, format="png", bbox_inches="tight", dpi=120)
            b64 = base64.b64encode(buf.getvalue()).decode()
            with open(path, "w") as fh:
                fh.write(
                    f'<html><body><img alt="{name}" '
                    f'src="data:image/png;base64,{b64}"/></body></html>'
                )
        elif fmt == "json":
            with open(path, "w") as fh:
                json.dump(_fig_to_dict(fig), fh)
        else:
            raise ValueError(f"unknown format {fmt!r}")
        written.append(path)
    return written


def save_all(figures: dict, outdir: str = ".", formats: tuple[str, ...] = ("png",)) -> list[str]:
    """Save {name: figure}; returns all written paths."""
    out = []
    for name, fig in figures.items():
        out.extend(save(fig, name, outdir, formats))
    return out


def _fig_to_dict(fig) -> dict:
    axes_out = []
    for ax in fig.get_axes():
        lines = [
            {
                "label": ln.get_label(),
                "x": [float(v) for v in ln.get_xdata()],
                "y": [float(v) for v in ln.get_ydata()],
            }
            for ln in ax.get_lines()
        ]
        axes_out.append(
            {
                "title": ax.get_title(),
                "xlabel": ax.get_xlabel(),
                "ylabel": ax.get_ylabel(),
                "xlim": [float(v) for v in ax.get_xlim()],
                "ylim": [float(v) for v in ax.get_ylim()],
                "lines": lines,
            }
        )
    return {"axes": axes_out}
