"""Figure saver: png / html / json export (reference ugvc/reports/nexusplt.py:41-89).

The reference saves matplotlib figures as png, mpld3 html, and mpld3 json.
mpld3 is not in this image, so:

- ``html`` renders the serialized line data as an INTERACTIVE inline-SVG
  page (hover readout of the nearest data point, click-to-toggle series)
  — the mpld3-html equivalent with zero dependencies — with the static
  png embedded as a fallback when a figure carries no line data;
- ``json`` serializes the axes data (lines/labels/limits), enough for
  downstream dashboards to re-plot.
"""

from __future__ import annotations

import base64
import html as _html
import io
import json
import os


def save(fig, name: str, outdir: str = ".", formats: tuple[str, ...] = ("png",)) -> list[str]:
    """Save a matplotlib figure under each format; returns written paths."""
    os.makedirs(outdir, exist_ok=True)
    written = []
    for fmt in formats:
        path = os.path.join(outdir, f"{name}.{fmt}")
        # names come from report inputs (sample names, file stems) — a
        # '../'-carrying name must not write outside outdir
        if os.path.commonpath([os.path.abspath(outdir), os.path.abspath(path)]) \
                != os.path.abspath(outdir):
            raise ValueError(f"figure name escapes output directory: {name!r}")
        if fmt == "png":
            fig.savefig(path, format="png", bbox_inches="tight", dpi=120)
        elif fmt == "html":
            try:  # non-numeric (e.g. datetime) axes cannot serialize
                data = _fig_to_dict(fig)
                interactive = any(ax["lines"] for ax in data["axes"])
            except (TypeError, ValueError):
                interactive = False
            buf = io.BytesIO()
            fig.savefig(buf, format="png", bbox_inches="tight", dpi=120)
            b64 = base64.b64encode(buf.getvalue()).decode()
            with open(path, "w") as fh:
                if interactive:
                    fh.write(_interactive_html(name, data, b64))
                else:  # no serializable line data: static fallback page
                    fh.write(
                        f'<html><body><img alt="{_html.escape(name, quote=True)}" '
                        f'src="data:image/png;base64,{b64}"/></body></html>'
                    )
        elif fmt == "json":
            with open(path, "w") as fh:
                json.dump(_fig_to_dict(fig), fh)
        else:
            raise ValueError(f"unknown format {fmt!r}")
        written.append(path)
    return written


def save_all(figures: dict, outdir: str = ".", formats: tuple[str, ...] = ("png",)) -> list[str]:
    """Save {name: figure}; returns all written paths."""
    out = []
    for name, fig in figures.items():
        out.extend(save(fig, name, outdir, formats))
    return out


_PALETTE = ["#1f77b4", "#ff7f0e", "#2ca02c", "#d62728", "#9467bd",
            "#8c564b", "#e377c2", "#7f7f7f", "#bcbd22", "#17becf"]

_JS = """
function render(figEl, FIG) {
  const W = 560, H = 320, M = {l: 55, r: 12, t: 28, b: 40};
  FIG.axes.forEach((ax) => {
    if (!ax.lines.length) return;
    const svgNS = 'http://www.w3.org/2000/svg';
    const wrap = document.createElement('div');
    const svg = document.createElementNS(svgNS, 'svg');
    svg.setAttribute('width', W); svg.setAttribute('height', H);
    svg.style.border = '1px solid #ccc'; svg.style.background = '#fff';
    const [x0, x1] = ax.xlim, [y0, y1] = ax.ylim;
    const sx = v => M.l + (v - x0) / (x1 - x0 || 1) * (W - M.l - M.r);
    const sy = v => H - M.b - (v - y0) / (y1 - y0 || 1) * (H - M.t - M.b);
    const txt = (s, x, y, a) => { const t = document.createElementNS(svgNS, 'text');
      t.textContent = s; t.setAttribute('x', x); t.setAttribute('y', y);
      t.setAttribute('font-size', '11'); if (a) t.setAttribute('text-anchor', a);
      svg.appendChild(t); return t; };
    txt(ax.title, W / 2, 16, 'middle');
    txt(ax.xlabel, W / 2, H - 8, 'middle');
    txt(ax.ylabel, 12, H / 2, 'middle').setAttribute('transform',
      `rotate(-90 12 ${H / 2})`);
    const tip = txt('', 0, 0); tip.setAttribute('font-weight', 'bold');
    const polys = ax.lines.map((ln, li) => {
      const p = document.createElementNS(svgNS, 'polyline');
      p.setAttribute('points', ln.x.map((v, i) => `${sx(v)},${sy(ln.y[i])}`).join(' '));
      p.setAttribute('fill', 'none'); p.setAttribute('stroke', PALETTE[li % PALETTE.length]);
      p.setAttribute('stroke-width', '1.6'); svg.appendChild(p); return p; });
    svg.addEventListener('mousemove', (ev) => {
      const r = svg.getBoundingClientRect();
      const mx = ev.clientX - r.left, my = ev.clientY - r.top;
      let best = null, bd = 1e18;
      ax.lines.forEach((ln, li) => ln.x.forEach((v, i) => {
        const d = (sx(v) - mx) ** 2 + (sy(ln.y[i]) - my) ** 2;
        if (d < bd) { bd = d; best = [v, ln.y[i], li]; } }));
      if (best && bd < 900) {
        tip.textContent = `${ax.lines[best[2]].label || 'series ' + best[2]}: ` +
          `(${best[0].toPrecision(4)}, ${best[1].toPrecision(4)})`;
        tip.setAttribute('x', M.l + 4); tip.setAttribute('y', M.t + 2);
      } else tip.textContent = ''; });
    const legend = document.createElement('div');
    ax.lines.forEach((ln, li) => {
      const b = document.createElement('span');
      b.textContent = '\\u25A0 ' + (ln.label || 'series ' + li);
      b.style.color = PALETTE[li % PALETTE.length];
      b.style.cursor = 'pointer'; b.style.marginRight = '10px';
      b.onclick = () => { const hid = polys[li].style.display === 'none';
        polys[li].style.display = hid ? '' : 'none';
        b.style.opacity = hid ? 1 : 0.35; };
      legend.appendChild(b); });
    wrap.appendChild(svg); wrap.appendChild(legend); figEl.appendChild(wrap);
  });
}
"""


def _interactive_html(name: str, data: dict, png_b64: str) -> str:
    """Self-contained interactive page: SVG lines + hover readout +
    legend toggles, static png fallback behind a details fold.

    Figure names and axis/series labels come from report inputs (sample
    names, file stems), so everything interpolated into markup is
    html-escaped, and the figure data rides in a JSON script block with
    ``</`` escaped — a label containing ``</script>`` or quotes must not
    break (or script-inject) a shared report artifact."""
    safe_name = _html.escape(name, quote=True)
    # <-escape EVERY '<' (json.dumps only emits '<' inside strings):
    # '</script>' would close the data block, and '<!--' would flip the
    # parser into the double-escaped script state so the real close tag
    # stops terminating it
    fig_json = json.dumps(data).replace("<", "\\u003c")
    return (
        "<html><head><meta charset='utf-8'>"
        f"<title>{safe_name}</title></head><body>\n"
        f"<div id='fig'></div>\n"
        f"<details><summary>static image</summary>"
        f"<img alt='{safe_name}' src='data:image/png;base64,{png_b64}'/></details>\n"
        f"<script type='application/json' id='fig-data'>{fig_json}</script>\n"
        f"<script>\nconst PALETTE = {json.dumps(_PALETTE)};\n"
        "const FIG = JSON.parse(document.getElementById('fig-data').textContent);\n"
        f"{_JS}\n"
        "render(document.getElementById('fig'), FIG);\n"
        "</script></body></html>\n"
    )


def _fig_to_dict(fig) -> dict:
    axes_out = []
    for ax in fig.get_axes():
        lines = [
            {
                "label": ln.get_label(),
                "x": [float(v) for v in ln.get_xdata()],
                "y": [float(v) for v in ln.get_ydata()],
            }
            for ln in ax.get_lines()
        ]
        axes_out.append(
            {
                "title": ax.get_title(),
                "xlabel": ax.get_xlabel(),
                "ylabel": ax.get_ylabel(),
                "xlim": [float(v) for v in ax.get_xlim()],
                "ylim": [float(v) for v in ax.get_ylim()],
                "lines": lines,
            }
        )
    return {"axes": axes_out}
