"""Per-category accuracy tables, error-type decomposition, cumulative PR curves.

Parity target: ugvc/reports/report_utils.py (ErrorType :50-57, category
filters :508-538, performance math :415-505, SEC re-filter :71-75). The
reference computes the cumulative PR curve with row-wise pandas ``apply``;
here the whole curve is vectorized (sort + cumsum + elementwise safe
divides) and the per-category masks are plain boolean algebra, so a
40-category report is a handful of array passes. Plotting/IPython display
are optional: tables always compute; figures save to PNG when a plot dir
is given (headless-safe, no notebook required).
"""

from __future__ import annotations

from configparser import ConfigParser
from enum import Enum

import numpy as np
import pandas as pd

from variantcalling_tpu.utils.stats_utils import get_f1, get_precision, get_recall


def parse_config(config_file: str):
    """VarReport INI section -> (parameters, param_names) (reference :18-47)."""
    parser = ConfigParser()
    parser.read(config_file)
    param_names = ["run_id", "pipeline_version", "h5_concordance_file"]
    parameters = {p: parser.get("VarReport", p) for p in param_names}
    parameters["verbosity"] = parser.get("VarReport", "verbosity", fallback="5")
    param_names.append("verbosity")
    parameters["reference_version"] = parser.get("VarReport", "reference_version", fallback="hg38")
    parameters["truth_sample_name"] = parser.get("VarReport", "truth_sample_name", fallback="NA")
    parameters["h5outfile"] = parser.get("VarReport", "h5_output", fallback="var_report.h5")
    parameters["trained_w_gt"] = parser.get("VarReport", "h5_model_file", fallback=None)
    if parameters["truth_sample_name"]:
        param_names.append("truth_sample_name")
    for opt in ("model_name_with_gt", "model_name_without_gt", "model_pkl_with_gt", "model_pkl_without_gt", "model_name"):
        val = parser.get("VarReport", opt, fallback=None)
        if val:
            parameters[opt] = val
            param_names.append(opt)
    return parameters, param_names


class ErrorType(Enum):
    NOISE = 1
    NO_VARIANT = 2
    HOM_TO_HET = 3
    HET_TO_HOM = 4
    WRONG_ALLELE = 5
    NO_ERROR = 6


# the category set used by createVarReport (reference :508-538)
DEFAULT_CATEGORIES = [
    "SNP",
    "Indel",
    "non-hmer Indel",
    "hmer Indel <=4",
    "hmer Indel >4,<=8",
    "hmer Indel >8,<=10",
    "hmer Indel >10,<=12",
    "hmer Indel >12,<=14",
    "hmer Indel >15,<=19",
    "hmer Indel >=20",
]


def filter_by_category(data: pd.DataFrame, cat: str) -> pd.DataFrame:
    """Reference category algebra (:508-538)."""
    indel = data["indel"].astype(bool)
    hmer = data["hmer_length"]
    if cat == "SNP":
        return data[~indel]
    if cat == "Indel":
        return data[indel]
    if cat == "non-hmer Indel":
        return data[indel & (hmer == 0) & (data["indel_length"] > 0)]
    if cat == "non-hmer Indel w/o LCR":
        # the LCR annotation column name varies by reference build
        # (LCR-hs38 / LCR-hg19_tab_no_chr, report_data_loader.py:94-103);
        # without one the category degrades to plain non-hmer Indel
        lcr_cols = [c for c in data.columns if str(c).startswith("LCR")]
        lcr = data[lcr_cols[0]].astype(bool) if lcr_cols else pd.Series(False, index=data.index)
        return data[indel & (hmer == 0) & (data["indel_length"] > 0) & ~lcr]
    if cat == "hmer Indel <=4":
        return data[indel & (hmer > 0) & (hmer <= 4)]
    if cat == "hmer Indel >4,<=8":
        return data[indel & (hmer > 4) & (hmer <= 8)]
    if cat == "hmer Indel >8,<=10":
        return data[indel & (hmer > 8) & (hmer <= 10)]
    if cat == "hmer Indel >10,<=12":
        return data[indel & (hmer > 10) & (hmer <= 12)]
    if cat == "hmer Indel >12,<=14":
        return data[indel & (hmer > 12) & (hmer <= 14)]
    if cat == "hmer Indel >15,<=19":
        return data[indel & (hmer > 14) & (hmer <= 19)]
    if cat == "hmer Indel >=20":
        return data[indel & (hmer >= 20)]
    for i in range(1, 10):
        if cat == f"hmer Indel {i:d}":
            return data[indel & (hmer == i)]
    raise RuntimeError(f"No such category: {cat}")


def has_sec(x) -> bool:
    return x is not None and not pd.isna(x) and "SEC" in str(x)


class ReportUtils:
    def __init__(self, verbosity, h5outfile: str, num_plots_in_row: int = 6, min_value: float = 0.2, plot_dir: str | None = None):
        self.verbosity = int(verbosity)
        self.h5outfile = h5outfile
        self.min_value = min_value
        self.num_plots_in_row = num_plots_in_row
        self.score_name = "tree_score"
        self.plot_dir = plot_dir

    # -- public analysis surface (reference :67-126) ----------------------

    def basic_analysis(self, data: pd.DataFrame, categories: list[str], out_key: str, out_key_sec: str | None = None):
        data_sec = None
        if out_key_sec is not None and "blacklst" in data.columns:
            sec_df = data.copy()
            is_sec = sec_df["blacklst"].apply(has_sec)
            sec_df.loc[is_sec, "filter"] = "SEC"
            sec_df.loc[is_sec & (sec_df["classify_gt"] == "tp"), "classify_gt"] = "fn"
            data_sec = sec_df[~(is_sec & (sec_df["classify_gt"] == "fp"))]

        opt_tab, opt_res, perf_curve, error_types_tab = self.get_performance(data, categories)

        if data_sec is not None:
            sec_opt_tab, _sec_opt_res, _, sec_error_types_tab = self.get_performance(data_sec, categories)
            self._to_hdf(sec_opt_tab.copy(), out_key_sec)
            self._to_hdf(sec_error_types_tab, f"{out_key_sec}_error_types")

        if self.plot_dir and self.verbosity > 1:
            self.plot_performance(perf_curve, opt_res, list(categories), out_key)

        out = opt_tab.copy()
        self.make_multi_index(out)
        self._to_hdf(out, out_key)
        self._to_hdf(error_types_tab, f"{out_key}_error_types")
        return opt_tab, error_types_tab

    def homozygous_genotyping_analysis(self, d: pd.DataFrame, categories: list[str], out_key: str):
        hmz = d[(d["gt_ground_truth"].isin([(1, 1), "1/1", "1|1"])) & (d["classify"] != "fn")]
        opt_tab, _, _, _ = self.get_performance(hmz, categories)
        out = opt_tab.copy()
        self.make_multi_index(out)
        self._to_hdf(out, out_key)
        return opt_tab

    def base_stratification_analysis(self, d: pd.DataFrame, categories: list[str], bases: tuple) -> pd.DataFrame:
        base_data = d[
            (~d["indel"].astype(bool) & ((d["ref"] == bases[0]) | (d["ref"] == bases[1])))
            | ((d["hmer_length"] > 0) & ((d["hmer_indel_nuc"] == bases[0]) | (d["hmer_indel_nuc"] == bases[1])))
        ]
        opt_tab, _, _, _ = self.get_performance(base_data, categories)
        opt_tab = opt_tab.rename(index={a: f"{a} ({bases[0]}/{bases[1]})" for a in opt_tab.index})
        return opt_tab

    def get_performance(self, data: pd.DataFrame, categories: list[str]):
        perf_curve: dict[str, pd.DataFrame] = {}
        opt_res: dict[str, dict] = {}
        opt_rows = []
        err_rows = []
        for cat in categories:
            d = filter_by_category(data, cat)
            performance_dict, pr_curve = self.calc_performance(d)
            perf_curve[cat] = pr_curve
            opt_res[cat] = performance_dict
            opt_rows.append(self._general_performance_row(cat, performance_dict))
            if self.verbosity > 1:
                err_rows.append(self._error_types_row(cat, performance_dict))
        opt_tab = pd.concat(opt_rows) if opt_rows else pd.DataFrame()
        error_types_table = pd.concat(err_rows) if err_rows else pd.DataFrame()
        return opt_tab, opt_res, perf_curve, error_types_table

    # -- core math (reference :415-505, vectorized) -----------------------

    def calc_performance(self, data: pd.DataFrame) -> tuple[dict, pd.DataFrame]:
        score_name = self.score_name
        d = data
        call = d["call"].fillna("NA") if "call" in d else pd.Series("NA", index=d.index)
        base = d["base"].fillna("NA") if "base" in d else pd.Series("NA", index=d.index)
        filt = d["filter"].astype(str)
        score_raw = pd.to_numeric(d[score_name], errors="coerce")
        tp_mask = d["tp"].to_numpy(dtype=bool)
        fp_mask = d["fp"].to_numpy(dtype=bool)
        fn_mask = d["fn"].to_numpy(dtype=bool)

        # orient score so PASS scores high (reference :436-440)
        is_pass = (filt == "PASS").to_numpy()
        finite = score_raw.notna().to_numpy()
        score_pass = score_raw[is_pass & finite].head(20).mean()
        score_not_pass = score_raw[~is_pass & finite].head(20).mean()
        # default to ascending when either side has no scored records
        dir_switch = -1 if (not pd.isna(score_pass) and not pd.isna(score_not_pass) and score_pass <= score_not_pass) else 1
        score = score_raw.to_numpy(dtype=float) * dir_switch
        if np.any(np.isfinite(score)):
            score = score - np.nanmin(score)

        missing_candidates_index = (base == "FN").to_numpy() & (call == "NA").to_numpy()
        missing_candidates = int(missing_candidates_index.sum())
        score = np.where(missing_candidates_index, -1, score)

        filtered_tp = int((tp_mask & ~is_pass).sum())
        filtered_fp = int((fp_mask & ~is_pass).sum())
        initial_fp = int(fp_mask.sum())
        initial_tp = int(tp_mask.sum())
        initial_fn = int(fn_mask.sum())
        total_variants = initial_tp + initial_fn
        fp = initial_fp - filtered_fp
        fn = initial_fn + filtered_tp
        tp = initial_tp - filtered_tp

        if "error_type" in d:
            et = d["error_type"]
            noise = int(((et == ErrorType.NOISE) & is_pass).sum())
            hom_to_het = int(((et == ErrorType.HOM_TO_HET) & is_pass).sum())
            het_to_hom = int(((et == ErrorType.HET_TO_HOM) & is_pass).sum())
            wrong_allele = int(((et == ErrorType.WRONG_ALLELE) & is_pass).sum())
        else:
            noise = hom_to_het = het_to_hom = wrong_allele = 0
        filtered_true = fn - missing_candidates - hom_to_het - het_to_hom - wrong_allele

        recall = get_recall(fn, tp, np.nan)
        max_recall = get_recall(missing_candidates, tp + fn - missing_candidates, np.nan)
        precision = get_precision(fp, tp, np.nan)
        f1 = get_f1(recall, precision, np.nan)

        result_dict = {
            "# pos": total_variants,
            "recall": recall,
            "precision": precision,
            "f1": f1,
            "max_recall": max_recall,
            "initial_tp": initial_tp,
            "initial_fp": initial_fp,
            "initial_fn": initial_fn,
            "tp": tp,
            "fp": fp,
            "fn": fn,
            "noise": noise,
            "wrong_allele": wrong_allele,
            "hom->het": hom_to_het,
            "het->hom": het_to_hom,
            "filter_true": filtered_true,
            "miss_candidate": missing_candidates,
        }
        if len(d) < 10:
            return result_dict, pd.DataFrame()

        # cumulative PR curve: one sort + three cumsums (reference row-apply :494-503)
        order = np.argsort(score, kind="stable")
        tp_s = tp_mask[order].astype(np.int64)
        fp_s = fp_mask[order].astype(np.int64)
        cum_tp = np.cumsum(tp_s)
        fn_c = initial_fn + cum_tp
        tp_c = initial_tp - cum_tp
        fp_c = initial_fp - np.cumsum(fp_s)
        with np.errstate(invalid="ignore", divide="ignore"):
            rec = np.where(tp_c + fn_c > 0, tp_c / np.maximum(tp_c + fn_c, 1), np.nan)
            prec = np.where(tp_c + fp_c > 0, tp_c / np.maximum(tp_c + fp_c, 1), np.nan)
            f1_c = 2 * rec * prec / np.where(rec + prec > 0, rec + prec, np.nan)
        pr_curve = pd.DataFrame(
            {score_name: score[order], "recall": rec, "precision": prec, "f1": f1_c}
        )
        return result_dict, pr_curve

    # -- table/plot shaping ------------------------------------------------

    def _general_performance_row(self, cat, p):
        if self.verbosity > 1:
            return pd.DataFrame(
                {
                    "# pos": p["# pos"],
                    "# neg": p["initial_fp"],
                    "fn": p["initial_fn"],
                    "max recall": p["max_recall"],
                    "recall": p["recall"],
                    "precision": p["precision"],
                    "F1": p["f1"],
                },
                index=[cat],
            )
        return pd.DataFrame(
            {
                "true-vars": p["# pos"],
                "fn": p["initial_fn"],
                "fp": p["initial_fp"],
                "recall": p["recall"],
                "precision": p["precision"],
                "F1": p["f1"],
            },
            index=[cat],
        )

    @staticmethod
    def _error_types_row(cat, p):
        return pd.DataFrame(
            {
                "noise": p["noise"],
                "wrong_allele": p["wrong_allele"],
                "hom->het": p["hom->het"],
                "het->hom": p["het->hom"],
                "filter_true": p["filter_true"],
                "miss_candidate": p["miss_candidate"],
            },
            index=[cat],
        )

    # reference indel_analysis factor grid (report_utils.py:225-232)
    INDEL_VARIABLES = ("indel_length", "hmer_length", "max_vaf", "qual", "gq", "dp")
    INDEL_MINS = (1, 0, 0, 0, 0, 0)
    INDEL_MAXS = (15, 20, 1, 80, 80, 80)
    INDEL_BINS = (1, 1, 0.05, 3, 3, 3)

    def indel_analysis(self, data: pd.DataFrame, data_name: str) -> pd.DataFrame:
        """Per-factor indel error histograms + per-bin precision/recall.

        Reference report_utils.py:225-305 renders 5-panel matplotlib grids
        per (factor × hmer/non-hmer) inline; here the same numbers land in
        one long-format frame (h5 key ``{name}_indel_analysis``) with
        columns [group, variable, bin_left, ins_fp/tp/fn, del_fp/tp/fn,
        precision, recall] plus optional PNG grids under ``plot_dir``.
        Insertions/deletions are split per bin; hmer and non-hmer indels
        are separate groups, as in the reference plots.
        """
        indels = data[data["indel"].astype(bool)]
        hmer_len = np.nan_to_num(np.asarray(indels.get("hmer_length", 0), dtype=float))
        groups = (("hmer_indels", hmer_len > 0), ("non_hmer_indels", hmer_len == 0))
        rows = []
        for k, variable in enumerate(self.INDEL_VARIABLES):
            if variable not in indels.columns:
                continue
            lo, hi, width = self.INDEL_MINS[k], self.INDEL_MAXS[k], self.INDEL_BINS[k]
            if hi > 1:
                hi += 1
            bins = np.arange(lo, hi + width / 2, width)
            vals = np.asarray(indels[variable], dtype=float)
            is_ins = np.asarray(indels["indel_classify"] == "ins")
            for gname, gmask in groups:
                counts = {}
                for cls in ("fp", "tp", "fn"):
                    cmask = np.asarray(indels[cls], dtype=bool) & gmask
                    for side, smask in (("ins", is_ins), ("del", ~is_ins)):
                        v = vals[cmask & smask]
                        counts[f"{side}_{cls}"], _ = np.histogram(v[~np.isnan(v)], bins=bins)
                tp = counts["ins_tp"] + counts["del_tp"]
                fp = counts["ins_fp"] + counts["del_fp"]
                fn = counts["ins_fn"] + counts["del_fn"]
                with np.errstate(invalid="ignore", divide="ignore"):
                    precision = np.where(tp + fp > 0, tp / np.maximum(tp + fp, 1), np.nan)
                    recall = np.where(tp + fn > 0, tp / np.maximum(tp + fn, 1), np.nan)
                for b in range(len(bins) - 1):
                    rows.append({
                        "group": gname, "variable": variable, "bin_left": bins[b],
                        **{key: int(cnt[b]) for key, cnt in counts.items()},
                        "precision": precision[b], "recall": recall[b],
                    })
                if self.plot_dir and self.verbosity > 2:
                    self._plot_indel_panel(data_name, gname, variable, bins, counts,
                                           precision, recall)
        out = pd.DataFrame(rows)
        safe = data_name.replace("-", "_").replace(" ", "_")
        if len(out):
            self._to_hdf(out, f"{safe}_indel_analysis")
        return out

    def _plot_indel_panel(self, data_name, gname, variable, bins, counts, precision, recall):
        import os

        import matplotlib

        matplotlib.use("Agg")
        import matplotlib.pyplot as plt

        fig, ax = plt.subplots(1, 5, figsize=(15, 3))
        centers = bins[:-1]
        for i, cls in enumerate(("fp", "tp", "fn")):
            ax[i].bar(centers, counts[f"ins_{cls}"], width=np.diff(bins), alpha=0.5, label="ins",
                      align="edge")
            ax[i].bar(centers, counts[f"del_{cls}"], width=np.diff(bins), alpha=0.5, label="del",
                      color="g", align="edge")
            ax[i].set_title(cls)
            ax[i].set_xlabel(variable)
            ax[i].legend()
        ax[3].plot(centers, precision, "-o", markersize=3)
        ax[3].set_title("precision")
        ax[4].plot(centers, recall, "-o", markersize=3)
        ax[4].set_title("recall")
        fig.suptitle(f"{data_name} {gname} — {variable}")
        fig.tight_layout()
        os.makedirs(self.plot_dir, exist_ok=True)
        safe = f"{data_name}_{gname}_{variable}".replace("/", "_").replace(" ", "_")
        fig.savefig(os.path.join(self.plot_dir, f"indel_{safe}.png"))
        plt.close(fig)

    @staticmethod
    def make_multi_index(df: pd.DataFrame) -> None:
        """Multi-index columns before h5 save, for backwards compatibility."""
        df.columns = pd.MultiIndex.from_tuples([("whole genome", x) for x in df.columns])

    @staticmethod
    def get_anchor(anchor_id: str) -> str:
        return f"<a class ='anchor' id='{anchor_id}'> </a>"

    def _to_hdf(self, df: pd.DataFrame, key: str) -> None:
        from variantcalling_tpu.utils.h5_utils import write_hdf

        out = df.copy()
        if isinstance(out.columns, pd.MultiIndex):
            out.columns = ["|".join(map(str, t)) for t in out.columns]
        write_hdf(out, self.h5outfile, key=key, mode="a")

    def plot_performance(self, perf_curve: dict, opt_res: dict, categories: list[str], name: str, opt_res_sec=None):
        """PR + score-accuracy grids saved as PNGs under ``plot_dir``."""
        import math as _math
        import os

        import matplotlib

        matplotlib.use("Agg")
        import matplotlib.pyplot as plt

        m = self.num_plots_in_row
        categories = [c for c in categories if not any(c == f"hmer Indel {i}" for i in range(4, 10))]
        n = max(1, _math.ceil(len(categories) / m))
        fig_pr, ax_pr = plt.subplots(n, m, figsize=(3 * m, 3 * n + 0.5 * (n - 1)), squeeze=False)
        for k, cat in enumerate(categories):
            ax = ax_pr[k // m][k % m]
            perf = perf_curve.get(cat, pd.DataFrame())
            opt = opt_res.get(cat, {})
            if not perf.empty and not np.all(pd.isnull(perf["precision"])):
                ax.plot(perf["recall"], perf["precision"], "-", color="r")
                ax.plot(opt.get("recall"), opt.get("precision"), "o", color="red")
            ax.set_title(cat)
            ax.grid(True)
        fig_pr.suptitle(f"Precision/Recall curve ({name})", fontsize=20)
        fig_pr.tight_layout()
        os.makedirs(self.plot_dir, exist_ok=True)
        safe = name.replace("/", "_").replace(" ", "_")
        fig_pr.savefig(os.path.join(self.plot_dir, f"pr_{safe}.png"))
        plt.close(fig_pr)
