"""No-ground-truth callset statistics: indel hmer stats, AF histograms, SNP motifs.

Parity targets (ugvc/pipelines/run_no_gt_report.py, studied not copied):
- ``insertion_deletion_statistics`` :44-69 — hmer-indel counts per length
  1..12 × {ins,del} × {A/T, G/C}, split hom (1/1) vs het.
- ``allele_freq_hist`` :72-87 — per-variant-type AF histogram over 100 bins.
- ``snp_statistics`` :90-172 — SNP counts per (trinucleotide ref motif,
  alt) folded onto the 96 canonical (center A/C) classes by reverse
  complement.

All three run as batched device reductions over class-code vectors (one-hot
matmul / bincount), not per-record pandas loops.
"""

from __future__ import annotations

import itertools

import numpy as np
import pandas as pd

import jax.numpy as jnp

from variantcalling_tpu.featurize import classify_alleles, gather_windows
from variantcalling_tpu.io.fasta import FastaReader, revcomp
from variantcalling_tpu.io.vcf import VariantTable
from variantcalling_tpu.ops.features import hmer_indel_features

_BASES = "ACGT"


def _annotate(table: VariantTable, ref_fasta: str):
    cols = classify_alleles(table)
    with FastaReader(ref_fasta) as fa:
        windows = gather_windows(table, fa, radius=12)
    hmer_len, hmer_nuc = (
        np.asarray(x)
        for x in hmer_indel_features(
            jnp.asarray(windows), 12, jnp.asarray(cols.is_indel), jnp.asarray(cols.indel_nuc)
        )
    )
    return cols, windows, hmer_len, hmer_nuc


def insertion_deletion_statistics(
    table: VariantTable, cols, hmer_len: np.ndarray, hmer_nuc: np.ndarray, sample: int = 0
) -> dict[str, pd.DataFrame]:
    """{'homo','hete'} -> (4 × 12) hmer count frames (index ins A/ins G/del A/del G)."""
    gts = table.genotypes(sample)
    hom = (gts[:, 0] == 1) & (gts[:, 1] == 1)

    # class code per variant: (ins/del) × (A/T vs G/C) = 4 classes; -1 n/a
    is_at = (hmer_nuc == 0) | (hmer_nuc == 3)
    is_gc = (hmer_nuc == 1) | (hmer_nuc == 2)
    cls = np.where(
        cols.is_ins & is_at, 0, np.where(cols.is_ins & is_gc, 1, np.where(is_at, 2, np.where(is_gc, 3, -1)))
    )
    valid = cols.is_indel & (hmer_len >= 1) & (hmer_len <= 12) & (cls >= 0)

    def tally(zygosity_mask: np.ndarray) -> pd.DataFrame:
        m = valid & zygosity_mask
        # fused one-hot count over (class × length) on device
        code = cls[m] * 12 + (hmer_len[m] - 1)
        counts = np.asarray(jnp.bincount(jnp.asarray(code), length=48)).reshape(4, 12)
        return pd.DataFrame(counts, index=["ins A", "ins G", "del A", "del G"], columns=range(1, 13))

    return {"homo": tally(hom), "hete": tally(~hom)}


def variant_type_labels(cols, hmer_len: np.ndarray) -> np.ndarray:
    """snp / h-indel / non-h-indel labels (annotate_concordance convention)."""
    return np.where(
        cols.is_snp, "snp", np.where(cols.is_indel & (hmer_len > 0), "h-indel", "non-h-indel")
    )


def allele_freq_hist(table: VariantTable, vtype: np.ndarray, nbins: int = 100, sample: int = 0,
                     af: np.ndarray | None = None) -> pd.DataFrame:
    """Per-variant-type AF histogram (VAF from FORMAT/VAF|AF, else AD/DP).

    ``af`` accepts a precomputed allele-fraction vector so callers that
    also need it (the AF scatters) pay the per-record parse once.
    """
    if af is None:
        af = _compute_af(table, sample)
    result = {}
    edges = np.linspace(0, 1, nbins + 1)
    for group in pd.unique(vtype):
        vals = af[(vtype == group) & ~np.isnan(af)]
        hist = np.asarray(jnp.histogram(jnp.asarray(vals), bins=jnp.asarray(edges))[0]) if len(vals) else np.zeros(nbins, dtype=np.int64)
        result[group] = pd.Series(hist)
    return pd.DataFrame(result)


def _compute_af(table: VariantTable, sample: int = 0) -> np.ndarray:
    n = len(table)
    for key in ("VAF", "AF"):
        raw = table.format_field(key, sample)
        if any(r not in (None, ".", "") for r in raw):
            out = np.full(n, np.nan)
            for i, r in enumerate(raw):
                if r not in (None, ".", ""):
                    try:
                        out[i] = float(r.split(",")[0])
                    except ValueError:
                        pass
            return out
    ad = table.format_numeric("AD", sample=sample, missing=np.nan)
    dp = table.format_numeric("DP", sample=sample, max_len=1, missing=np.nan)
    if ad.shape[1] >= 2:
        with np.errstate(invalid="ignore", divide="ignore"):
            return np.where(dp[:, 0] > 0, ad[:, 1] / dp[:, 0], np.nan)
    return np.full(n, np.nan)


def motif_index_96() -> pd.MultiIndex:
    """The 96 canonical (trinucleotide with center A/C, alt != center) classes."""
    return pd.MultiIndex.from_tuples(
        [
            x
            for x in itertools.product(
                ["".join(m) for m in itertools.product(_BASES, repeat=3)], list(_BASES)
            )
            if x[0][1] != x[1] and x[0][1] in ("A", "C")
        ],
        names=["ref_motif", "alt_1"],
    )


def _fold_table() -> np.ndarray:
    """(64, 4) -> canonical class id 0..95 (or -1): static fold map.

    Center G/T motifs map via reverse complement of (motif, alt); built
    once host-side, applied as a device gather.
    """
    canon = {t: i for i, t in enumerate(motif_index_96())}
    out = np.full((64, 4), -1, dtype=np.int32)
    for m in range(64):
        motif = _BASES[m // 16] + _BASES[(m // 4) % 4] + _BASES[m % 4]
        for a in range(4):
            alt = _BASES[a]
            if motif[1] == alt:
                continue
            key = (motif, alt) if motif[1] in ("A", "C") else (revcomp(motif), revcomp(alt))
            out[m, a] = canon[key]
    return out


def snp_statistics(table: VariantTable, cols, windows: np.ndarray, center: int = 12,
                   exclude: np.ndarray | None = None) -> pd.Series:
    """96-class folded SNP motif counts as one device bincount.

    ``exclude`` masks records already consumed elsewhere (adjacent-SNV
    pairs reclassified as DBS78 doublets must not also count as SBS96 —
    the SigProfilerMatrixGenerator convention)."""
    m = cols.is_snp & (cols.ref_code < 4) & (cols.alt_code < 4)
    if exclude is not None:
        m = m & ~exclude
    left = windows[m, center - 1].astype(np.int64)
    mid = cols.ref_code[m].astype(np.int64)
    right = windows[m, center + 1].astype(np.int64)
    ok = (left < 4) & (right < 4)
    motif_code = left[ok] * 16 + mid[ok] * 4 + right[ok]
    alt_code = cols.alt_code[m][ok].astype(np.int64)
    fold = _fold_table()
    cls = fold[motif_code, alt_code]
    cls = cls[cls >= 0]
    counts = np.asarray(jnp.bincount(jnp.asarray(cls), length=96)) if len(cls) else np.zeros(96, dtype=np.int64)
    return pd.Series(counts.astype(np.int64), index=motif_index_96(), name="size")
