"""VariantEval-equivalent summary tables as in-process device reductions.

The reference shells out to GATK VariantEval and text-parses nine tables
(ugvc/pipelines/run_no_gt_report.py:195-256: CompOverlap, CountVariants,
TiTvVariantEvaluator, IndelLengthHistogram, IndelSummary,
MetricsCollection, ValidationReport, VariantSummary, MultiallelicSummary).
Here each table is a masked reduction over the columnar variant table,
stratified by dbSNP novelty (all / known / novel) like VariantEval's
default Novelty stratifier. Counting runs as one fused device program:
per-variant class codes -> one-hot sums.
"""

from __future__ import annotations

import numpy as np
import pandas as pd

import jax.numpy as jnp

from variantcalling_tpu.featurize import classify_alleles
from variantcalling_tpu.io.vcf import VariantTable, read_vcf

# transitions: A<->G, C<->T
_TRANSITION = {(0, 2), (2, 0), (1, 3), (3, 1)}

EVAL_TABLES = [
    "CompOverlap",
    "CountVariants",
    "TiTvVariantEvaluator",
    "IndelLengthHistogram",
    "IndelSummary",
    "MetricsCollection",
    "ValidationReport",
    "VariantSummary",
    "MultiallelicSummary",
]


def dbsnp_membership(table: VariantTable, dbsnp_vcf: str) -> np.ndarray:
    """Bool per variant: (chrom, pos, ref, first-alt) present in dbSNP."""
    db = read_vcf(dbsnp_vcf, drop_format=True)
    keys = set()
    for i in range(len(db)):
        for alt in db.alt[i].split(","):
            keys.add((str(db.chrom[i]), int(db.pos[i]), db.ref[i], alt))
    out = np.zeros(len(table), dtype=bool)
    for i in range(len(table)):
        alt = table.alt[i].split(",")[0]
        out[i] = (str(table.chrom[i]), int(table.pos[i]), table.ref[i], alt) in keys
    return out


def _class_counts(masks: dict[str, np.ndarray], strata: dict[str, np.ndarray]) -> pd.DataFrame:
    """One fused device reduction: (strata × classes) count matrix.

    masks: class-name -> bool (N,); strata: row-name -> bool (N,).
    Computed as a single (S, N) x (N, C) bool matmul on device — the MXU
    path for what VariantEval does with per-record Java loops.
    """
    names = list(masks)
    m = jnp.asarray(np.stack([masks[k] for k in names], axis=1), dtype=jnp.float32)  # (N, C)
    s = jnp.asarray(np.stack([strata[k] for k in strata], axis=0), dtype=jnp.float32)  # (S, N)
    counts = np.asarray(s @ m).astype(np.int64)  # (S, C)
    return pd.DataFrame(counts, columns=names, index=list(strata))


def compute_eval_tables(
    table: VariantTable,
    known: np.ndarray | None = None,
    sample: int = 0,
) -> dict[str, pd.DataFrame]:
    """All nine VariantEval-style tables from one columnar table."""
    n = len(table)
    cols = classify_alleles(table)
    gts = table.genotypes(sample) if table.n_samples else np.full((n, 2), -1, dtype=np.int8)
    known = np.zeros(n, dtype=bool) if known is None else known

    is_snp = cols.is_snp
    is_indel = cols.is_indel
    is_ins = cols.is_indel & cols.is_ins
    is_del = cols.is_indel & ~cols.is_ins
    is_multi = cols.n_alts > 1
    called = (gts >= 0).any(axis=1)
    het = called & (gts[:, 0] != gts[:, 1])
    hom_var = called & (gts[:, 0] == gts[:, 1]) & (gts[:, 0] > 0)
    # mixed/MNP/symbolic: not SNP, not indel, has alt
    has_alt = np.fromiter((a not in (".", "") for a in table.alt), dtype=bool, count=n)
    is_other = has_alt & ~is_snp & ~is_indel

    # transitions are exactly the |code diff| == 2 pairs (A0<->G2, C1<->T3)
    ti = is_snp & (np.abs(cols.ref_code - cols.alt_code) == 2)
    tv = is_snp & ~ti

    strata = {"all": np.ones(n, dtype=bool), "known": known, "novel": ~known}

    cv = _class_counts(
        {
            "nVariantLoci": has_alt,
            "nSNPs": is_snp,
            "nInsertions": is_ins,
            "nDeletions": is_del,
            "nMNPs": np.zeros(n, dtype=bool),
            "nMixed": is_other,
            "nHets": het & has_alt,
            "nHomVar": hom_var & has_alt,
            "nMultiAllelic": is_multi,
        },
        strata,
    ).reset_index(names="Novelty")
    cv["variantRate"] = np.nan
    cv["hetHomRatio"] = np.where(cv["nHomVar"] > 0, cv["nHets"] / np.maximum(cv["nHomVar"], 1), np.nan)

    titv = _class_counts({"nTi": ti, "nTv": tv}, strata).reset_index(names="Novelty")
    titv["tiTvRatio"] = np.where(titv["nTv"] > 0, titv["nTi"] / np.maximum(titv["nTv"], 1), 0.0)

    comp = _class_counts({"nEvalVariants": has_alt, "novelSites": ~known & has_alt, "nVariantsAtComp": known}, strata)
    comp = comp.reset_index(names="Novelty")
    comp["compRate"] = 100.0 * comp["nVariantsAtComp"] / np.maximum(comp["nEvalVariants"], 1)
    comp["concordantRate"] = comp["compRate"]

    # indel length histogram: -10..10 (deletions negative), VariantEval layout
    lengths = np.where(is_ins, cols.indel_length, -cols.indel_length)
    lengths = lengths[is_indel & (np.abs(np.where(is_indel, lengths, 0)) <= 10)]
    bins = np.arange(-10, 11)
    freq = np.asarray(jnp.sum(jnp.asarray(lengths[None, :]) == jnp.asarray(bins[:, None]), axis=1)) if len(lengths) else np.zeros(21, dtype=np.int64)
    ilh = pd.DataFrame({"Length": bins, "Freq": freq})
    ilh = ilh[ilh["Length"] != 0]

    n_snp_all = int(is_snp.sum())
    n_ins = int(is_ins.sum())
    n_del = int(is_del.sum())
    isum = _class_counts(
        {"n_SNPs": is_snp, "n_indels": is_indel, "n_insertions": is_ins, "n_deletions": is_del},
        strata,
    ).reset_index(names="Novelty")
    isum["SNP_to_indel_ratio"] = isum["n_SNPs"] / np.maximum(isum["n_indels"], 1)
    isum["insertion_to_deletion_ratio"] = isum["n_insertions"] / np.maximum(isum["n_deletions"], 1)

    msum = _class_counts(
        {"nSNPs": is_snp, "nMultiSNPs": is_snp & is_multi, "nIndels": is_indel, "nMultiIndels": is_indel & is_multi},
        strata,
    ).reset_index(names="Novelty")

    vsum = pd.DataFrame(
        {
            "nSamples": [table.n_samples],
            "nSNPs": [n_snp_all],
            "nIndels": [n_ins + n_del],
            "nSVs": [0],
            "TiTvRatio": [float(titv.loc[titv["Novelty"] == "all", "tiTvRatio"].iloc[0])],
        }
    )

    metrics = pd.DataFrame(
        {
            "metric": ["nSNPs", "nIndels", "insertionDeletionRatio", "tiTvRatio"],
            "value": [
                n_snp_all,
                n_ins + n_del,
                n_ins / max(n_del, 1),
                float(vsum["TiTvRatio"].iloc[0]),
            ],
        }
    )

    validation = pd.DataFrame(
        {
            "nComp": [int(known.sum())],
            "TP": [int(known.sum())],
            "FP": [0],
            "FN": [0],
            "sensitivity": [100.0],
        }
    )

    return {
        "CompOverlap": comp,
        "CountVariants": cv,
        "TiTvVariantEvaluator": titv,
        "IndelLengthHistogram": ilh,
        "IndelSummary": isum,
        "MetricsCollection": metrics,
        "ValidationReport": validation,
        "VariantSummary": vsum,
        "MultiallelicSummary": msum,
    }
