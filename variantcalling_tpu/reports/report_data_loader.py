"""Concordance-frame loader for the variant report.

Parity target: ugvc/reports/report_data_loader.py:8-126 — loads the
run_comparison h5 (key ``all``), derives fp/fn/tp masks, max_vaf, qual
fallback, and the per-variant ErrorType from (ground-truth, called)
genotype pairs. Genotypes here are ``j/k`` strings (the columnar frame's
representation); error typing is vectorized over parsed allele sets.
"""

from __future__ import annotations

import numpy as np
import pandas as pd

from variantcalling_tpu.reports.report_utils import ErrorType
from variantcalling_tpu.utils.h5_utils import read_hdf

COMMON_COLUMNS = [
    "indel",
    "hmer_indel_length",
    "tree_score",
    "filter",
    "blacklst",
    "classify",
    "classify_gt",
    "indel_length",
    "hmer_indel_nuc",
    "well_mapped_coverage",
    "base",
    "call",
    "gt_ground_truth",
    "gt_ultima",
    "ad",
    "dp",
    "vaf",
    "ref",
    "alleles",
    "gc_content",
    "indel_classify",
    "qual",
    "gq",
]


def _gt_set(g) -> frozenset:
    """'0/1' | '1|1' | './.' | tuple -> set of allele ints (None for '.')."""
    if isinstance(g, tuple):
        return frozenset(g)
    if g is None or (isinstance(g, float) and np.isnan(g)):
        return frozenset({None})
    parts = str(g).replace("|", "/").split("/")
    return frozenset(None if p in (".", "") else int(p) for p in parts)


def get_error_type(gtr, call) -> ErrorType:
    """Reference decision tree (report_data_loader.py:106-126)."""
    gtr_gt = _gt_set(gtr)
    call_gt = _gt_set(call)
    if gtr_gt == call_gt:
        return ErrorType.NO_ERROR
    if gtr_gt in (frozenset({0}), frozenset({None})):
        return ErrorType.NOISE
    if call_gt in (frozenset({0}), frozenset({None})):
        return ErrorType.NO_VARIANT
    if gtr_gt & call_gt == gtr_gt:
        return ErrorType.HOM_TO_HET
    if gtr_gt & call_gt == call_gt:
        return ErrorType.HET_TO_HOM
    return ErrorType.WRONG_ALLELE


class ReportDataLoader:
    def __init__(self, concordance_file: str, reference_version: str = "hg38", exome_column_name: str = "exome.twist"):
        self.concordance_file = concordance_file
        self.reference_version = reference_version
        self.columns = self._columns_subset(exome_column_name)
        self.rename_dict = self._rename_dict()

    def load_concordance_df(self) -> pd.DataFrame:
        df = read_hdf(
            self.concordance_file, key="all", skip_keys=["concordance", "input_args"], columns_subset=self.columns
        )
        df = df.rename(columns=self.rename_dict)
        df["fp"] = (df["call"] == "FP") | (df["call"] == "FP_CA")
        df["fn"] = (df["base"] == "FN") | (df["base"] == "FN_CA")
        df["tp"] = df["call"] == "TP"
        if "vaf" not in df.columns:
            with np.errstate(invalid="ignore", divide="ignore"):
                ad1 = df["ad"].apply(lambda x: float(str(x).split(",")[1]) if isinstance(x, str) and "," in x else 0.0)
                df["vaf"] = ad1 / df["dp"].replace(0, np.nan)
        df["max_vaf"] = df["vaf"].apply(lambda x: 0 if isinstance(x, float) and np.isnan(x) else (max(x) if isinstance(x, (tuple, list)) else x))
        if "qual" not in df or (~df["qual"].isna()).sum() == 0:
            df["qual"] = df["tree_score"]
        df["error_type"] = [
            get_error_type(g, u) for g, u in zip(df["gt_ground_truth"], df["gt_ultima"])
        ]
        df = df.rename(columns={"hmer_indel_length": "hmer_length"})
        return df

    def load_sv_concordance_df(self) -> tuple[dict, dict]:
        import pickle

        with open(self.concordance_file, "rb") as f:
            data = pickle.load(f)
        dfs_no_gt = {k: v for k, v in data.items() if k.endswith("counts")}
        dfs_with_gt = {k: v for k, v in data.items() if not k.endswith("counts")}
        return dfs_no_gt, dfs_with_gt

    def _rename_dict(self):
        if self.reference_version == "hg38":
            return {"LCR-hs38": "LCR"}
        if self.reference_version == "hg19":
            return {
                "LCR-hg19_tab_no_chr": "LCR",
                "mappability.hg19.0_tab_no_chr": "mappability.0",
                "ug_hcr_hg19_no_chr": "ug_hcr",
            }
        return {}

    def _columns_subset(self, exome_column_name):
        cols = COMMON_COLUMNS + [exome_column_name]
        if self.reference_version == "hg38":
            return cols + ["LCR-hs38", "mappability.0", "ug_hcr", "callable"]
        if self.reference_version == "hg19":
            return cols + ["LCR-hg19_tab_no_chr", "mappability.hg19.0_tab_no_chr", "ug_hcr_hg19_no_chr", "callable"]
        return cols
