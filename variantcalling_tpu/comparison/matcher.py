"""Haplotype-aware variant matching (the vcfeval-equivalent core).

The reference delegates TP/FP/FN matching to rtg-tools vcfeval as a black
box (docs/run_comparison_pipeline.md:3-5, SURVEY §2.5). This module is a
native re-derivation of the *behavior*: two callsets match when some
assignment of their variants onto haplotypes yields identical sequence —
so different representations (split/joined multiallelics, shifted indels)
still pair up.

Pipeline per contig:

1. **normalize** every variant (trim shared suffix then prefix per allele)
   so trivially different encodings share a key;
2. **exact match** on (pos, ref, alt-set) — resolves the overwhelming
   majority of loci in one vectorized join;
3. **local haplotype search** for the residue: cluster unmatched call +
   truth variants within a merge window, then try all diploid phasings of
   each side (capped combinatorics, as vcfeval caps its search) and accept
   clusters whose {hap1, hap2} sequence sets agree. Matched clusters mark
   their variants tp (genotype-consistent by construction).

Genotype-ignoring classification (`classify`) counts allele-level hits;
`classify_gt` additionally requires genotype equality (exact stage) or
phase-consistency (haplotype stage).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

MAX_CLUSTER_VARIANTS = 8  # per side; larger clusters fall back to exact-only
MAX_HETS = 6  # 2^6 phasings per side, mirrors vcfeval's bounded search
CLUSTER_GAP = 30  # bp between cluster members
FLANK = 10  # reference padding around a cluster


def normalize_variant(pos: int, ref: str, alt: str) -> tuple[int, str, str]:
    """Trim shared suffix, then shared prefix (VT/bcftools-norm semantics).

    1-based pos; returns the minimal (pos, ref, alt) representation.
    """
    while len(ref) > 1 and len(alt) > 1 and ref[-1] == alt[-1]:
        ref = ref[:-1]
        alt = alt[:-1]
    while len(ref) > 1 and len(alt) > 1 and ref[0] == alt[0]:
        ref = ref[1:]
        alt = alt[1:]
        pos += 1
    return pos, ref, alt


@dataclass
class SideVariants:
    """Per-contig columnar view of one side (calls or truth)."""

    pos: np.ndarray  # int64, 1-based (original)
    ref: list[str]
    alts: list[list[str]]
    gt: np.ndarray  # (n, 2) int8, -1 = missing
    _norm_keys: list[frozenset] | None = None  # lazy: Python matcher only

    @property
    def norm_keys(self) -> list[frozenset]:
        """Per-variant set of normalized (pos, ref, alt) — computed on first
        use so the native matcher path never pays for the Python loop."""
        if self._norm_keys is None:
            keys = []
            for i in range(len(self.pos)):
                ks = []
                for a in self.alts[i]:
                    if a in (".", "", "*", "<NON_REF>") or a.startswith("<"):
                        continue
                    ks.append(normalize_variant(int(self.pos[i]), self.ref[i], a))
                keys.append(frozenset(ks))
            self._norm_keys = keys
        return self._norm_keys


def make_side(pos: np.ndarray, ref: list[str], alts: list[list[str]], gt: np.ndarray) -> SideVariants:
    return SideVariants(np.asarray(pos, dtype=np.int64), list(ref), [list(a) for a in alts],
                        np.asarray(gt, dtype=np.int8))


def _called_allele_keys(side: SideVariants, i: int) -> frozenset:
    """Normalized keys of the alleles the genotype actually calls (all alts if no GT)."""
    g = side.gt[i]
    called_idx = {int(a) for a in g if a > 0}
    if not called_idx:
        return side.norm_keys[i]
    out = []
    for ai in sorted(called_idx):
        if ai - 1 < len(side.alts[i]):
            a = side.alts[i][ai - 1]
            if a in (".", "", "*", "<NON_REF>") or a.startswith("<"):
                continue
            out.append(normalize_variant(int(side.pos[i]), side.ref[i], a))
    return frozenset(out)


@dataclass
class MatchResult:
    call_tp: np.ndarray  # bool per call: allele-level match
    call_tp_gt: np.ndarray  # bool per call: genotype-level match
    truth_tp: np.ndarray  # bool per truth
    truth_tp_gt: np.ndarray
    # per-call index of matched truth record (-1 = none) for gt/error columns
    call_truth_idx: np.ndarray


def match_contig(calls: SideVariants, truth: SideVariants, ref_seq: str,
                 haplotype_rescue: bool = True) -> MatchResult:
    """Per-contig match. Dispatches to the native (C++) engine when built;
    this Python implementation is the specification and the fallback
    (native parity is locked by tests/unit/test_matcher_native.py)."""
    native_res = _match_contig_native(calls, truth, ref_seq, haplotype_rescue)
    if native_res is not None:
        return native_res
    return _match_contig_py(calls, truth, ref_seq, haplotype_rescue)


def _match_contig_native(calls: SideVariants, truth: SideVariants, ref_seq: str,
                         haplotype_rescue: bool) -> MatchResult | None:
    from variantcalling_tpu import native

    if not native.available():
        return None
    out = native.match_contig_native(
        ref_seq,
        # "" joined list = no alts; empty-string entries map to "." (both
        # are symbolic to the spec) so [""] round-trips unambiguously
        calls.pos, calls.ref, [",".join(x or "." for x in a) for a in calls.alts], calls.gt,
        truth.pos, truth.ref, [",".join(x or "." for x in a) for a in truth.alts], truth.gt,
        haplotype_rescue=haplotype_rescue,
    )
    if out is None:
        return None
    call_tp, call_tp_gt, truth_tp, truth_tp_gt, idx = out
    return MatchResult(call_tp, call_tp_gt, truth_tp, truth_tp_gt, idx)


def _match_contig_py(calls: SideVariants, truth: SideVariants, ref_seq: str,
                     haplotype_rescue: bool = True) -> MatchResult:
    nc, nt = len(calls.pos), len(truth.pos)
    call_tp = np.zeros(nc, dtype=bool)
    call_tp_gt = np.zeros(nc, dtype=bool)
    truth_tp = np.zeros(nt, dtype=bool)
    truth_tp_gt = np.zeros(nt, dtype=bool)
    call_truth_idx = np.full(nc, -1, dtype=np.int64)

    # ---- stage 2: exact normalized-key join ------------------------------
    truth_by_key: dict = {}
    for j in range(nt):
        for k in _called_allele_keys(truth, j):
            truth_by_key.setdefault(k, j)
    for i in range(nc):
        ck = _called_allele_keys(calls, i)
        if not ck:
            continue
        hits = {k: truth_by_key[k] for k in ck if k in truth_by_key}
        if len(hits) == len(ck):  # every called allele present in truth
            j = next(iter(hits.values()))
            call_tp[i] = True
            call_truth_idx[i] = j
            for jj in set(hits.values()):
                truth_tp[jj] = True
            # genotype equality: same multiset of normalized called alleles
            # AND same zygosity pattern
            if len(set(hits.values())) == 1 and _gt_equivalent(calls, i, truth, j):
                call_tp_gt[i] = True
                truth_tp_gt[j] = True

    # ---- stage 3: local haplotype search on the residue ------------------
    # Two passes with the same bounded search. Pass 1 clusters the
    # allele-level residue (exact-join misses): a match sets both levels.
    # Pass 2 clusters the remaining genotype-level residue — a cluster whose
    # diploid haplotype sets agree is genotype-consistent by construction,
    # so split-vs-joined multiallelics (call het G + het T vs truth G/T)
    # recover classify_gt (vcfeval semantics). Running the allele pass first
    # keeps genotype errors (allele-matched, gt-mismatched sites) from
    # joining — and poisoning — allele-level clusters.
    if not haplotype_rescue:
        # representation-strict mode: exact normalized-key joins only — the
        # run_comparison --disable_reinterpretation contract (the reference's
        # "reinterpretation" stage repairs vcfeval representation artifacts;
        # here that repair IS the haplotype search, so disabling maps to
        # skipping stage 3; docs/run_comparison_pipeline.md:78)
        return MatchResult(call_tp, call_tp_gt, truth_tp, truth_tp_gt, call_truth_idx)

    failed: set = set()  # pass-1 clusters that already failed; identical
    # pass-2 clusters (no gt-only members joined) are skipped, not re-searched
    for level in ("allele", "genotype"):
        if level == "allele":
            un_c = np.nonzero(~call_tp)[0]
            un_t = np.nonzero(~truth_tp)[0]
        else:
            un_c = np.nonzero(~call_tp_gt)[0]
            un_t = np.nonzero(~truth_tp_gt)[0]
        for c_idx, t_idx in _clusters(calls, truth, un_c, un_t):
            if not c_idx or not t_idx:
                continue
            ckey = (tuple(c_idx), tuple(t_idx))
            if ckey in failed:
                continue
            if level == "allele":
                failed.add(ckey)  # removed below on success
            if len(c_idx) > MAX_CLUSTER_VARIANTS or len(t_idx) > MAX_CLUSTER_VARIANTS:
                continue
            lo = min(min(int(calls.pos[i]) for i in c_idx), min(int(truth.pos[j]) for j in t_idx)) - FLANK
            hi = max(
                max(int(calls.pos[i]) + len(calls.ref[i]) for i in c_idx),
                max(int(truth.pos[j]) + len(truth.ref[j]) for j in t_idx),
            ) + FLANK
            lo = max(lo, 1)
            window = ref_seq[lo - 1 : hi - 1]
            haps_c = _diploid_haplotypes(calls, c_idx, lo, window)
            haps_t = _diploid_haplotypes(truth, t_idx, lo, window)
            if haps_c is None or haps_t is None:
                continue
            if haps_c & haps_t:
                failed.discard(ckey)
                for i in c_idx:
                    call_tp[i] = True
                    call_tp_gt[i] = True
                for j in t_idx:
                    truth_tp[j] = True
                    truth_tp_gt[j] = True

    return MatchResult(call_tp, call_tp_gt, truth_tp, truth_tp_gt, call_truth_idx)


def match_tables(calls, truth, fasta) -> MatchResult:
    """Whole-genome match of two VariantTables: per-contig match_contig sweep.

    Returns a MatchResult over the full (unsplit) record order of each
    table. Shared by run_comparison and vcfeval_flavors.
    """
    contigs = list(dict.fromkeys(list(calls.chrom) + list(truth.chrom)))
    nc, nt = len(calls), len(truth)
    res = MatchResult(
        np.zeros(nc, dtype=bool),
        np.zeros(nc, dtype=bool),
        np.zeros(nt, dtype=bool),
        np.zeros(nt, dtype=bool),
        np.full(nc, -1, dtype=np.int64),
    )
    for contig in contigs:
        cm = np.asarray(calls.chrom) == contig
        tm = np.asarray(truth.chrom) == contig
        if contig not in fasta.references:
            continue
        seq = fasta.fetch(contig, 0, fasta.get_reference_length(contig))
        cs = make_side(
            calls.pos[cm],
            list(calls.ref[cm]),
            [a.split(",") if a not in (".", "") else [] for a in calls.alt[cm]],
            calls.genotypes()[cm],
        )
        ts = make_side(
            truth.pos[tm],
            list(truth.ref[tm]),
            [a.split(",") if a not in (".", "") else [] for a in truth.alt[tm]],
            truth.genotypes()[tm],
        )
        r = match_contig(cs, ts, seq)
        res.call_tp[cm] = r.call_tp
        res.call_tp_gt[cm] = r.call_tp_gt
        res.truth_tp[tm] = r.truth_tp
        res.truth_tp_gt[tm] = r.truth_tp_gt
        # remap per-contig truth indices to global
        t_global = np.nonzero(tm)[0]
        matched = r.call_truth_idx >= 0
        glob = np.full(len(r.call_truth_idx), -1, dtype=np.int64)
        glob[matched] = t_global[r.call_truth_idx[matched]]
        res.call_truth_idx[cm] = glob
    return res


def _gt_equivalent(calls: SideVariants, i: int, truth: SideVariants, j: int) -> bool:
    """Same zygosity over equivalent alleles (allele indices may differ)."""

    def pattern(side: SideVariants, k: int) -> tuple:
        g = [int(a) for a in side.gt[k] if a >= 0]
        if not g:
            return ("any",)
        keys = []
        for a in sorted(g):
            if a == 0:
                keys.append(("ref",))
            elif a - 1 < len(side.alts[k]):
                keys.append(normalize_variant(int(side.pos[k]), side.ref[k], side.alts[k][a - 1]))
        return tuple(sorted(map(str, keys)))

    pc, pt = pattern(calls, i), pattern(truth, j)
    return pc == pt or pc == ("any",) or pt == ("any",)


def _clusters(calls: SideVariants, truth: SideVariants, un_c: np.ndarray, un_t: np.ndarray):
    """Group leftover variants (both sides) into gap-bounded position clusters."""
    events = [(int(calls.pos[i]), 0, int(i)) for i in un_c] + [(int(truth.pos[j]), 1, int(j)) for j in un_t]
    events.sort()
    cur_c: list[int] = []
    cur_t: list[int] = []
    last = None
    for pos, side, idx in events:
        if last is not None and pos - last > CLUSTER_GAP and (cur_c or cur_t):
            yield cur_c, cur_t
            cur_c, cur_t = [], []
        (cur_c if side == 0 else cur_t).append(idx)
        last = pos
    if cur_c or cur_t:
        yield cur_c, cur_t


def _diploid_haplotypes(side: SideVariants, idx: list[int], lo: int, window: str) -> set | None:
    """All {hap_a, hap_b} sequence pairs over the window, one per phasing.

    Returns None when the phasing space is too large or variants overlap
    (can't be replayed consistently).
    """
    hets = []
    applied = []  # (start0, end0, alt, which) which: 2=both, 0/1 het slot
    for k in idx:
        g = [int(a) for a in side.gt[k] if a >= 0]
        alleles = sorted({a for a in g if a > 0}) or ([1] if side.alts[k] else [])
        for ai in alleles:
            if ai - 1 >= len(side.alts[k]):
                return None
            alt = side.alts[k][ai - 1]
            if alt in (".", "", "*", "<NON_REF>") or alt.startswith("<"):
                continue
            s0 = int(side.pos[k]) - lo
            e0 = s0 + len(side.ref[k])
            hom = len(g) >= 2 and g.count(ai) == len([a for a in g if a > 0]) and 0 not in g
            if hom:
                applied.append((s0, e0, alt, 2))
            else:
                applied.append((s0, e0, alt, len(hets)))
                hets.append(k)
    if len(hets) > MAX_HETS:
        return None

    out = set()
    for mask in range(1 << len(hets)):
        hap0, hap1 = [], []
        ok = True
        for s0, e0, alt, which in applied:
            if which == 2:
                hap0.append((s0, e0, alt))
                hap1.append((s0, e0, alt))
            else:
                target = hap0 if (mask >> which) & 1 == 0 else hap1
                target.append((s0, e0, alt))
        a = _apply(window, hap0)
        b = _apply(window, hap1)
        if a is None or b is None:
            ok = False
        if ok:
            out.add(frozenset((a, b)) if a != b else frozenset((a,)))
    return out if out else None


def _apply(window: str, edits: list[tuple[int, int, str]]) -> str | None:
    """Apply non-overlapping (start0, end0, alt) edits; None on overlap/ooband."""
    edits = sorted(edits)
    out = []
    cur = 0
    for s0, e0, alt in edits:
        if s0 < cur or e0 > len(window) or s0 < 0:
            return None
        out.append(window[cur:s0])
        out.append(alt)
        cur = e0
    out.append(window[cur:])
    return "".join(out)
