"""Haplotype-aware variant matching (the vcfeval-equivalent core).

The reference delegates TP/FP/FN matching to rtg-tools vcfeval as a black
box (docs/run_comparison_pipeline.md:3-5, SURVEY §2.5). This module is a
native re-derivation of the *behavior*: two callsets match when some
assignment of their variants onto haplotypes yields identical sequence —
so different representations (split/joined multiallelics, shifted indels)
still pair up.

Pipeline per contig:

1. **normalize** every variant (trim shared suffix then prefix per allele)
   so trivially different encodings share a key;
2. **exact match** on (pos, ref, alt-set) — resolves the overwhelming
   majority of loci in one vectorized join;
3. **local haplotype search** for the residue: cluster unmatched call +
   truth variants within a merge window, then try all diploid phasings of
   each side (capped combinatorics, as vcfeval caps its search) and accept
   clusters whose {hap1, hap2} sequence sets agree. Matched clusters mark
   their variants tp (genotype-consistent by construction).

Genotype-ignoring classification (`classify`) counts allele-level hits;
`classify_gt` additionally requires genotype equality (exact stage) or
phase-consistency (haplotype stage).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

MAX_CLUSTER_VARIANTS = 16  # per side; larger clusters fall back to exact-only
MAX_HETS = 12  # het edits per side considered by the phasing search
#: state cap for the dedup-BFS phasing search (_diploid_haplotypes): states
#: are UNORDERED partial haplotype pairs deduplicated per step, so the
#: mask/~mask symmetry plus equal-prefix merges keep real clusters far
#: below 2^hets; 4096 admits every h <= 13 exactly and more when merges
#: occur. Overflow falls back to exact-only matching (counted in stats).
PHASING_BEAM = 4096
CLUSTER_GAP = 30  # bp between cluster members
FLANK = 10  # reference padding around a cluster


def normalize_variant(pos: int, ref: str, alt: str) -> tuple[int, str, str]:
    """Trim shared suffix, then shared prefix (VT/bcftools-norm semantics).

    1-based pos; returns the minimal (pos, ref, alt) representation.
    """
    while len(ref) > 1 and len(alt) > 1 and ref[-1] == alt[-1]:
        ref = ref[:-1]
        alt = alt[:-1]
    while len(ref) > 1 and len(alt) > 1 and ref[0] == alt[0]:
        ref = ref[1:]
        alt = alt[1:]
        pos += 1
    return pos, ref, alt


@dataclass
class SideVariants:
    """Per-contig columnar view of one side (calls or truth)."""

    pos: np.ndarray  # int64, 1-based (original)
    ref: list[str]
    alts: list[list[str]]
    gt: np.ndarray  # (n, 2) int8, -1 = missing
    _norm_keys: list[frozenset] | None = None  # lazy: Python matcher only

    @property
    def norm_keys(self) -> list[frozenset]:
        """Per-variant set of normalized (pos, ref, alt) — computed on first
        use so the native matcher path never pays for the Python loop."""
        if self._norm_keys is None:
            keys = []
            for i in range(len(self.pos)):
                ks = []
                for a in self.alts[i]:
                    if a in (".", "", "*", "<NON_REF>") or a.startswith("<"):
                        continue
                    ks.append(normalize_variant(int(self.pos[i]), self.ref[i], a))
                keys.append(frozenset(ks))
            self._norm_keys = keys
        return self._norm_keys


def make_side(pos: np.ndarray, ref: list[str], alts: list[list[str]], gt: np.ndarray) -> SideVariants:
    return SideVariants(np.asarray(pos, dtype=np.int64), list(ref), [list(a) for a in alts],
                        np.asarray(gt, dtype=np.int8))


def _called_allele_keys(side: SideVariants, i: int) -> frozenset:
    """Normalized keys of the alleles the genotype actually calls (all alts if no GT)."""
    g = side.gt[i]
    called_idx = {int(a) for a in g if a > 0}
    if not called_idx:
        return side.norm_keys[i]
    out = []
    for ai in sorted(called_idx):
        if ai - 1 < len(side.alts[i]):
            a = side.alts[i][ai - 1]
            if a in (".", "", "*", "<NON_REF>") or a.startswith("<"):
                continue
            out.append(normalize_variant(int(side.pos[i]), side.ref[i], a))
    return frozenset(out)


@dataclass
class MatchResult:
    call_tp: np.ndarray  # bool per call: allele-level match
    call_tp_gt: np.ndarray  # bool per call: genotype-level match
    truth_tp: np.ndarray  # bool per truth
    truth_tp_gt: np.ndarray
    # per-call index of matched truth record (-1 = none) for gt/error columns
    call_truth_idx: np.ndarray
    # search-cap accounting (allele pass): clusters that degraded to
    # exact-only because of MAX_CLUSTER_VARIANTS / MAX_HETS / PHASING_BEAM,
    # and the variants they contained — the silent-accuracy risk VERDICT
    # r4 flagged is now measurable (see tests/unit/test_matcher_density.py)
    fallback_clusters: int = 0
    fallback_variants: int = 0


def match_contig(calls: SideVariants, truth: SideVariants, ref_seq: str,
                 haplotype_rescue: bool = True) -> MatchResult:
    """Per-contig match. Dispatches to the native (C++) engine when built;
    this Python implementation is the specification and the fallback
    (native parity is locked by tests/unit/test_matcher_native.py)."""
    native_res = _match_contig_native(calls, truth, ref_seq, haplotype_rescue)
    if native_res is not None:
        return native_res
    return _match_contig_py(calls, truth, ref_seq, haplotype_rescue)


def _match_contig_native(calls: SideVariants, truth: SideVariants, ref_seq: str,
                         haplotype_rescue: bool) -> MatchResult | None:
    from variantcalling_tpu import native

    if not native.available():
        return None
    out = native.match_contig_native(
        ref_seq,
        # "" joined list = no alts; empty-string entries map to "." (both
        # are symbolic to the spec) so [""] round-trips unambiguously
        calls.pos, calls.ref, [",".join(x or "." for x in a) for a in calls.alts], calls.gt,
        truth.pos, truth.ref, [",".join(x or "." for x in a) for a in truth.alts], truth.gt,
        haplotype_rescue=haplotype_rescue,
    )
    if out is None:
        return None
    call_tp, call_tp_gt, truth_tp, truth_tp_gt, idx, stats = out
    return MatchResult(call_tp, call_tp_gt, truth_tp, truth_tp_gt, idx,
                       fallback_clusters=int(stats[0]), fallback_variants=int(stats[1]))


def _match_contig_py(calls: SideVariants, truth: SideVariants, ref_seq: str,
                     haplotype_rescue: bool = True) -> MatchResult:
    nc, nt = len(calls.pos), len(truth.pos)
    call_tp = np.zeros(nc, dtype=bool)
    call_tp_gt = np.zeros(nc, dtype=bool)
    truth_tp = np.zeros(nt, dtype=bool)
    truth_tp_gt = np.zeros(nt, dtype=bool)
    call_truth_idx = np.full(nc, -1, dtype=np.int64)

    # ---- stage 2: exact normalized-key join ------------------------------
    truth_by_key: dict = {}
    for j in range(nt):
        for k in _called_allele_keys(truth, j):
            truth_by_key.setdefault(k, j)
    for i in range(nc):
        ck = _called_allele_keys(calls, i)
        if not ck:
            continue
        hits = {k: truth_by_key[k] for k in ck if k in truth_by_key}
        if len(hits) == len(ck):  # every called allele present in truth
            j = next(iter(hits.values()))
            call_tp[i] = True
            call_truth_idx[i] = j
            for jj in set(hits.values()):
                truth_tp[jj] = True
            # genotype equality: same multiset of normalized called alleles
            # AND same zygosity pattern
            if len(set(hits.values())) == 1 and _gt_equivalent(calls, i, truth, j):
                call_tp_gt[i] = True
                truth_tp_gt[j] = True

    # ---- stage 3: local haplotype search on the residue ------------------
    # Two passes with the same bounded search. Pass 1 clusters the
    # allele-level residue (exact-join misses): a match sets both levels.
    # Pass 2 clusters the remaining genotype-level residue — a cluster whose
    # diploid haplotype sets agree is genotype-consistent by construction,
    # so split-vs-joined multiallelics (call het G + het T vs truth G/T)
    # recover classify_gt (vcfeval semantics). Running the allele pass first
    # keeps genotype errors (allele-matched, gt-mismatched sites) from
    # joining — and poisoning — allele-level clusters.
    if not haplotype_rescue:
        # representation-strict mode: exact normalized-key joins only — the
        # run_comparison --disable_reinterpretation contract (the reference's
        # "reinterpretation" stage repairs vcfeval representation artifacts;
        # here that repair IS the haplotype search, so disabling maps to
        # skipping stage 3; docs/run_comparison_pipeline.md:78)
        return MatchResult(call_tp, call_tp_gt, truth_tp, truth_tp_gt, call_truth_idx)

    fb_clusters = fb_variants = 0
    failed: set = set()  # pass-1 clusters that already failed; identical
    # pass-2 clusters (no gt-only members joined) are skipped, not re-searched
    for level in ("allele", "genotype"):
        if level == "allele":
            un_c = np.nonzero(~call_tp)[0]
            un_t = np.nonzero(~truth_tp)[0]
        else:
            un_c = np.nonzero(~call_tp_gt)[0]
            un_t = np.nonzero(~truth_tp_gt)[0]
        for c_idx, t_idx in _clusters(calls, truth, un_c, un_t):
            if not c_idx or not t_idx:
                continue
            ckey = (tuple(c_idx), tuple(t_idx))
            if ckey in failed:
                continue
            if level == "allele":
                failed.add(ckey)  # removed below on success
            if len(c_idx) > MAX_CLUSTER_VARIANTS or len(t_idx) > MAX_CLUSTER_VARIANTS:
                if level == "allele":
                    fb_clusters += 1
                    fb_variants += len(c_idx) + len(t_idx)
                continue
            lo = min(min(int(calls.pos[i]) for i in c_idx), min(int(truth.pos[j]) for j in t_idx)) - FLANK
            hi = max(
                max(int(calls.pos[i]) + len(calls.ref[i]) for i in c_idx),
                max(int(truth.pos[j]) + len(truth.ref[j]) for j in t_idx),
            ) + FLANK
            lo = max(lo, 1)
            window = ref_seq[lo - 1 : hi - 1]
            haps_c, capped_c = _diploid_haplotypes(calls, c_idx, lo, window)
            haps_t, capped_t = _diploid_haplotypes(truth, t_idx, lo, window)
            if haps_c is None or haps_t is None:
                if (capped_c or capped_t) and level == "allele":
                    fb_clusters += 1
                    fb_variants += len(c_idx) + len(t_idx)
                continue
            if haps_c & haps_t:
                failed.discard(ckey)
                for i in c_idx:
                    call_tp[i] = True
                    call_tp_gt[i] = True
                for j in t_idx:
                    truth_tp[j] = True
                    truth_tp_gt[j] = True

    return MatchResult(call_tp, call_tp_gt, truth_tp, truth_tp_gt, call_truth_idx,
                       fallback_clusters=fb_clusters, fallback_variants=fb_variants)


def match_tables(calls, truth, fasta) -> MatchResult:
    """Whole-genome match of two VariantTables: per-contig match_contig sweep.

    Returns a MatchResult over the full (unsplit) record order of each
    table. Shared by run_comparison and vcfeval_flavors.
    """
    contigs = list(dict.fromkeys(list(calls.chrom) + list(truth.chrom)))
    nc, nt = len(calls), len(truth)
    res = MatchResult(
        np.zeros(nc, dtype=bool),
        np.zeros(nc, dtype=bool),
        np.zeros(nt, dtype=bool),
        np.zeros(nt, dtype=bool),
        np.full(nc, -1, dtype=np.int64),
    )
    for contig in contigs:
        cm = np.asarray(calls.chrom) == contig
        tm = np.asarray(truth.chrom) == contig
        if contig not in fasta.references:
            continue
        seq = fasta.fetch(contig, 0, fasta.get_reference_length(contig))
        cs = make_side(
            calls.pos[cm],
            list(calls.ref[cm]),
            [a.split(",") if a not in (".", "") else [] for a in calls.alt[cm]],
            calls.genotypes()[cm],
        )
        ts = make_side(
            truth.pos[tm],
            list(truth.ref[tm]),
            [a.split(",") if a not in (".", "") else [] for a in truth.alt[tm]],
            truth.genotypes()[tm],
        )
        r = match_contig(cs, ts, seq)
        res.call_tp[cm] = r.call_tp
        res.call_tp_gt[cm] = r.call_tp_gt
        res.truth_tp[tm] = r.truth_tp
        res.truth_tp_gt[tm] = r.truth_tp_gt
        res.fallback_clusters += r.fallback_clusters
        res.fallback_variants += r.fallback_variants
        # remap per-contig truth indices to global
        t_global = np.nonzero(tm)[0]
        matched = r.call_truth_idx >= 0
        glob = np.full(len(r.call_truth_idx), -1, dtype=np.int64)
        glob[matched] = t_global[r.call_truth_idx[matched]]
        res.call_truth_idx[cm] = glob
    return res


def _gt_equivalent(calls: SideVariants, i: int, truth: SideVariants, j: int) -> bool:
    """Same zygosity over equivalent alleles (allele indices may differ)."""

    def pattern(side: SideVariants, k: int) -> tuple:
        g = [int(a) for a in side.gt[k] if a >= 0]
        if not g:
            return ("any",)
        keys = []
        for a in sorted(g):
            if a == 0:
                keys.append(("ref",))
            elif a - 1 < len(side.alts[k]):
                keys.append(normalize_variant(int(side.pos[k]), side.ref[k], side.alts[k][a - 1]))
        return tuple(sorted(map(str, keys)))

    pc, pt = pattern(calls, i), pattern(truth, j)
    return pc == pt or pc == ("any",) or pt == ("any",)


def _clusters(calls: SideVariants, truth: SideVariants, un_c: np.ndarray, un_t: np.ndarray):
    """Group leftover variants (both sides) into gap-bounded position clusters."""
    events = [(int(calls.pos[i]), 0, int(i)) for i in un_c] + [(int(truth.pos[j]), 1, int(j)) for j in un_t]
    events.sort()
    cur_c: list[int] = []
    cur_t: list[int] = []
    last = None
    for pos, side, idx in events:
        if last is not None and pos - last > CLUSTER_GAP and (cur_c or cur_t):
            yield cur_c, cur_t
            cur_c, cur_t = [], []
        (cur_c if side == 0 else cur_t).append(idx)
        last = pos
    if cur_c or cur_t:
        yield cur_c, cur_t


def _extend_hap(hap: tuple[str, int], window: str, s0: int, e0: int, alt: str):
    """Append one edit to a partial haplotype (built string, consumed-ref
    position); None on overlap/out-of-window — the incremental equivalent
    of :func:`_apply`'s validity check."""
    built, cur = hap
    if s0 < cur or e0 > len(window) or s0 < 0:
        return None
    return (built + window[cur:s0] + alt, e0)


def _diploid_haplotypes(side: SideVariants, idx: list[int], lo: int, window: str) -> set | None:
    """All {hap_a, hap_b} sequence pairs over the window, one per phasing.

    Enumerated by a dedup-BFS over sorted edits instead of 2^hets masks:
    the state set holds UNORDERED partial-haplotype pairs, so the
    mask/~mask symmetry and equal-prefix merges collapse the space —
    exact (not approximate) whenever the state count stays within
    PHASING_BEAM, which covers every cluster the old exhaustive search
    could do and far larger ones. Returns (pairs, capped): pairs is None
    when no phasing can be replayed OR the search was capped (MAX_HETS /
    beam overflow); capped distinguishes the two so callers can count the
    exact-only degradations.
    """
    n_hets = 0
    applied = []  # (start0, end0, alt, both_haps)
    for k in idx:
        g = [int(a) for a in side.gt[k] if a >= 0]
        alleles = sorted({a for a in g if a > 0}) or ([1] if side.alts[k] else [])
        for ai in alleles:
            if ai - 1 >= len(side.alts[k]):
                return None, False
            alt = side.alts[k][ai - 1]
            if alt in (".", "", "*", "<NON_REF>") or alt.startswith("<"):
                continue
            s0 = int(side.pos[k]) - lo
            e0 = s0 + len(side.ref[k])
            hom = len(g) >= 2 and g.count(ai) == len([a for a in g if a > 0]) and 0 not in g
            applied.append((s0, e0, alt, hom))
            n_hets += not hom
    if n_hets > MAX_HETS:
        return None, True

    # sorted edit order == _apply's replay order, so incremental overlap
    # rejection drops exactly the phasings the exhaustive search dropped
    applied.sort(key=lambda e: (e[0], e[1], e[2]))
    states: set = {(("", 0), ("", 0))}
    for s0, e0, alt, both in applied:
        new: set = set()
        for a, b in states:
            if both:
                na = _extend_hap(a, window, s0, e0, alt)
                nb = _extend_hap(b, window, s0, e0, alt)
                if na is not None and nb is not None:
                    new.add((na, nb) if na <= nb else (nb, na))
            else:
                na = _extend_hap(a, window, s0, e0, alt)
                if na is not None:
                    new.add((na, b) if na <= b else (b, na))
                nb = _extend_hap(b, window, s0, e0, alt)
                if nb is not None:
                    new.add((a, nb) if a <= nb else (nb, a))
        if not new:
            return None, False  # no phasing can replay these edits
        if len(new) > PHASING_BEAM:
            return None, True  # search capped: caller degrades to exact-only
        states = new

    out = set()
    for (abuilt, acur), (bbuilt, bcur) in states:
        a = abuilt + window[acur:]
        b = bbuilt + window[bcur:]
        out.add(frozenset((a, b)) if a != b else frozenset((a,)))
    return (out if out else None), False


def _apply(window: str, edits: list[tuple[int, int, str]]) -> str | None:
    """Apply non-overlapping (start0, end0, alt) edits; None on overlap/ooband."""
    edits = sorted(edits)
    out = []
    cur = 0
    for s0, e0, alt in edits:
        if s0 < cur or e0 > len(window) or s0 < 0:
            return None
        out.append(window[cur:s0])
        out.append(alt)
        cur = e0
    out.append(window[cur:])
    return "".join(out)
