"""Callset-vs-truth comparison: normalization, haplotype matching, annotation."""
