"""Pileup SNV caller + variant hit-fraction matching (fingerprinting core).

Replaces the reference's ``bcftools mpileup | bcftools view -i
'AD[0:1]/DP >= af'`` subprocess chain
(ugvc/comparison/variant_hit_fraction_caller.py:23-28) with an in-process
engine: BAM alignments are scattered into a (region_len × 4) allele-count
tensor host-side, and the AF gate + major-alt selection run as one batched
device kernel. Hit fraction joins called vs ground-truth variants on
(chrom, pos, ref, major_alt) exactly as the reference's pandas merge
(variant_hit_fraction_caller.py:30-49).
"""

from __future__ import annotations

import numpy as np

from variantcalling_tpu.io.bam import EXCLUDE_FLAGS, BamReader

_M_OPS = {0, 7, 8}  # CIGAR ops that consume both read and ref (M, =, X)
_BASES = "ACGT"
MAX_DEPTH = 500  # matches bcftools mpileup -d 500


def _cram_pileup_counts(cram_path: str, chrom: str, start: int, end: int,
                        ref_path: str | None) -> np.ndarray:
    """CRAM pileup via the native decoder's base reconstruction."""
    from variantcalling_tpu import native
    from variantcalling_tpu.io.cram import header_from_buffer
    from variantcalling_tpu.io.fasta import FastaReader

    if ref_path is None:
        raise ValueError("CRAM pileup needs the reference FASTA (ref_path)")
    with open(cram_path, "rb") as fh:
        buf = fh.read()
    header = header_from_buffer(buf, cram_path)
    if chrom not in header.references:
        return np.zeros((end - start, 4), dtype=np.int32)
    tid = header.references.index(chrom)
    with FastaReader(ref_path) as fa:
        ref_seq = fa.fetch(chrom, 0, fa.get_reference_length(chrom))
    counts = native.cram_pileup(buf, tid, start, end, ref_seq)
    if counts is None:
        raise ValueError(
            f"cannot pile up CRAM {cram_path}: unsupported codec or malformed "
            "stream (supported: CRAM 3.0, raw/gzip/rANS-4x8)"
        )
    np.minimum(counts, MAX_DEPTH, out=counts)  # same -d cap as the BAM path
    return counts


def pileup_counts(bam_path: str, chrom: str, start: int, end: int,
                  ref_path: str | None = None) -> np.ndarray:
    """(L, 4) int32 base counts over [start, end) of ``chrom`` (0-based).

    Skips unmapped/secondary/qcfail/dup reads (mpileup defaults) and
    indels (``--skip-indels``); depth capped at MAX_DEPTH per locus.
    CRAM inputs reconstruct bases natively and need ``ref_path``.
    """
    if str(bam_path).endswith(".cram"):
        return _cram_pileup_counts(bam_path, chrom, start, end, ref_path)
    length = end - start
    counts = np.zeros((length, 4), dtype=np.int32)
    with BamReader(bam_path, decode_seq=True) as reader:
        try:
            tid = reader.header.references.index(chrom)
        except ValueError:
            return counts
        for aln in reader:
            if aln.ref_id != tid or aln.flag & EXCLUDE_FLAGS or aln.seq is None:
                continue
            if aln.pos >= end:
                continue
            rpos = aln.pos  # ref cursor
            qpos = 0  # read cursor
            for op, ln in aln.cigar:
                if op in _M_OPS:
                    lo = max(rpos, start)
                    hi = min(rpos + ln, end)
                    if hi > lo:
                        q0 = qpos + (lo - rpos)
                        codes = aln.seq[q0 : q0 + (hi - lo)]
                        valid = codes < 4
                        idx = np.arange(lo - start, hi - start)[valid]
                        np.add.at(counts, (idx, codes[valid].astype(np.int64)), 1)
                    rpos += ln
                    qpos += ln
                elif op in (1, 4):  # I, S consume read
                    qpos += ln
                elif op in (2, 3):  # D, N consume ref
                    rpos += ln
    np.minimum(counts, MAX_DEPTH, out=counts)
    return counts


def call_snvs(counts: np.ndarray, ref_codes: np.ndarray, min_af: float) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """AF-gated SNV calls from a pileup tensor — one batched device program.

    Returns (offsets, major_alt_code, alt_fraction) for loci where the
    best non-reference allele has count/depth >= min_af (the reference's
    ``AD[0:1]/DP >= af`` gate) and depth > 0.
    """
    import jax.numpy as jnp

    c = jnp.asarray(counts)
    ref = jnp.asarray(ref_codes)
    depth = jnp.sum(c, axis=1)
    masked = jnp.where(jnp.arange(4)[None, :] == ref[:, None], -1, c)
    alt = jnp.argmax(masked, axis=1)
    alt_count = jnp.max(masked, axis=1)
    af = jnp.where(depth > 0, alt_count / jnp.maximum(depth, 1), 0.0)
    hit = (af >= min_af) & (depth > 0) & (ref < 4) & (alt_count > 0)
    hit = np.asarray(hit)
    return np.nonzero(hit)[0], np.asarray(alt)[hit], np.asarray(af)[hit]


class VariantHitFractionCaller:
    """Drop-in surface of the reference class (variant_hit_fraction_caller.py:15-73)."""

    def __init__(self, ref: str, out_dir: str, min_af_snps: float, region: str):
        self.ref = ref
        self.out_dir = out_dir
        self.min_af_snps = min_af_snps
        self.region = region

    def call_variants(self, bam: str, chrom: str, start: int, end: int, min_af: float) -> set[tuple[str, int, str, str]]:
        """Called SNVs as {(chrom, pos_1based, ref_base, major_alt)}."""
        from variantcalling_tpu.io.fasta import FastaReader

        counts = pileup_counts(bam, chrom, start, end, ref_path=self.ref)
        with FastaReader(self.ref) as fa:
            ref_seq = fa.fetch(chrom, start, min(end, fa.get_reference_length(chrom)))
        codes = np.full(end - start, 4, dtype=np.int8)
        for i, b in enumerate(ref_seq.upper()):
            if b in _BASES:
                codes[i] = _BASES.index(b)
        offs, alts, _af = call_snvs(counts, codes, min_af)
        return {(chrom, start + int(o) + 1, _BASES[codes[o]], _BASES[int(a)]) for o, a in zip(offs, alts)}

    @staticmethod
    def calc_hit_fraction(
        called: set[tuple[str, int, str, str]],
        ground_truth: set[tuple[str, int, str, str]],
    ) -> tuple[float, int, int]:
        """(hit_fraction, hit_count, ground_truth_count); +0.001 guard as reference."""
        hits = len(called & ground_truth)
        n_gt = len(ground_truth)
        return hits / (n_gt + 0.001), hits, n_gt

    @staticmethod
    def add_args_to_parser(parser) -> None:
        parser.add_argument("--max_vars", type=int, default=2000, help="max number of variants to check for concordance")
        parser.add_argument(
            "--min_af_snps", type=float, default=0.03, help="min allele frequency to count as a ground-truth hit"
        )
        parser.add_argument(
            "--min_af_germline_snps",
            type=float,
            default=0.1,
            help="min allele frequency to count a snp as germline snp, for normal-in-tumor <-> normal matching",
        )
        parser.add_argument(
            "--min_hit_fraction_target",
            type=float,
            default=0.99,
            help="fraction of ground-truth variants which has hits in target samples",
        )


def snp_set_from_vcf(vcf_path: str, region: tuple[str, int, int] | None, hcr=None) -> set[tuple[str, int, str, str]]:
    """Ground-truth SNP keys (chrom, pos, ref, first_alt) within region ∩ HCR."""
    from variantcalling_tpu.io.vcf import read_vcf

    table = read_vcf(vcf_path, region=region, drop_format=True)
    out: set[tuple[str, int, str, str]] = set()
    hcr_by_chrom = hcr.merged().by_chrom() if hcr is not None else None
    for i in range(len(table)):
        ref = table.ref[i]
        alts = table.alt[i].split(",")
        major = alts[0]
        if len(ref) != 1 or len(major) != 1 or major not in _BASES or ref not in _BASES:
            continue
        chrom, pos = str(table.chrom[i]), int(table.pos[i])
        if hcr_by_chrom is not None:
            if chrom not in hcr_by_chrom:
                continue
            s, e = hcr_by_chrom[chrom]
            j = np.searchsorted(s, pos - 1, side="right") - 1
            if j < 0 or pos - 1 >= e[j]:
                continue
        out.add((chrom, pos, ref, major))
    return out
