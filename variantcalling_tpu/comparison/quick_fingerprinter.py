"""Quick fingerprinting: match sample BAMs to known ground-truth callsets.

Drop-in behavioral surface of the reference
(ugvc/comparison/quick_fingerprinter.py:13-135): for every (sample, bam),
call AF-gated SNVs in a region, compute the hit fraction against every
ground truth (restricted to its HCR ∩ region, SNPs only), and error when a
bam fails to match its own truth (< min_hit_fraction_target) or matches a
different sample's truth (> target). All matching is in-process (pileup
kernel + set joins) — no samtools/bcftools/bedtools chain.
"""

from __future__ import annotations

import os

from variantcalling_tpu import logger
from variantcalling_tpu.comparison.pileup_caller import VariantHitFractionCaller, snp_set_from_vcf
from variantcalling_tpu.io.bed import read_bed


def parse_region(region: str) -> tuple[str, int, int]:
    """'chr15:26000000-26200000' → (chrom, start_0based, end_exclusive)."""
    chrom, span = region.split(":")
    lo, hi = span.replace(",", "").split("-")
    return chrom, int(lo) - 1, int(hi)


class QuickFingerprinter:
    def __init__(
        self,
        sample_crams: dict[str, list[str]],
        ground_truth_vcfs: dict[str, str],
        hcrs: dict[str, str],
        ref: str,
        region: str,
        min_af_snps: float,
        min_hit_fraction_target: float,
        out_dir: str,
    ):
        self.crams = sample_crams
        self.region = parse_region(region)
        self.min_hit_fraction_target = min_hit_fraction_target
        self.out_dir = out_dir
        os.makedirs(out_dir, exist_ok=True)
        self.vc = VariantHitFractionCaller(ref, out_dir, min_af_snps, region)
        chrom, start, end = self.region
        vcf_region = (chrom, start + 1, end)
        self.ground_truths_to_check = {
            sid: snp_set_from_vcf(ground_truth_vcfs[sid], vcf_region, read_bed(hcrs[sid]))
            for sid in ground_truth_vcfs
        }

    def check(self) -> None:
        errors: list[str] = []
        chrom, start, end = self.region
        with open(f"{self.out_dir}/quick_fingerprinting_results.txt", "w", encoding="utf-8") as of:
            for sample_id, bams in self.crams.items():
                of.write(f"Check consistency for {sample_id}:\n")
                for bam in bams:
                    called = self.vc.call_variants(bam, chrom, start, end, self.vc.min_af_snps)
                    max_hit_fraction, best_match = 0.0, None
                    potential_error = f"{bam} - {sample_id} "
                    for gt_id, gt_set in self.ground_truths_to_check.items():
                        hit_fraction, hits, n_gt = self.vc.calc_hit_fraction(called, gt_set)
                        of.write(f"{bam} - {sample_id} vs. {gt_id} hit_fraction={hit_fraction}\n")
                        with open(
                            f"{self.out_dir}/{os.path.basename(bam)}_{gt_id}.hit.txt", "w", encoding="utf-8"
                        ) as fh:
                            fh.write(f"hit_count {hits}\nhit_fraction {hit_fraction}\n")
                        if hit_fraction > max_hit_fraction:
                            max_hit_fraction, best_match = hit_fraction, gt_id
                        if sample_id == gt_id and hit_fraction < self.min_hit_fraction_target:
                            potential_error += f"does not match it's ground truth: hit_fraction={hit_fraction} "
                        elif sample_id != gt_id and hit_fraction > self.min_hit_fraction_target:
                            potential_error += f"matched ground truth of {gt_id}: hit_fraction={hit_fraction} "
                    if best_match != sample_id:
                        logger.warning("%s best_match=%s hit_fraction=%s", bam, best_match, max_hit_fraction)
                    if potential_error != f"{bam} - {sample_id} ":
                        errors.append(potential_error)
        if errors:
            raise RuntimeError("\n".join(errors))
