"""Cohort count aggregation as a mesh all-reduce (BASELINE config 5).

The reference's SEC training walks per-sample files on one box; at pod
scale the natural layout is samples sharded across devices and the cohort
noise tensor produced by one ``psum`` over the sample axis — ICI-resident,
no host gather. ``aggregate_on_mesh`` is that program: a shard_map whose
per-device body sums its local sample slab and psums across the mesh.
Multi-HOST cohorts (each host holding its own sample files) go through
``parallel.distributed.aggregate_counts_across_hosts``, the same psum
over a global mesh spanning every host's devices.
"""

from __future__ import annotations

import numpy as np

from jax.sharding import Mesh

from variantcalling_tpu.parallel.mesh import DATA_AXIS


def pad_samples_to_devices(sample_counts: np.ndarray, n_dev: int) -> np.ndarray:
    """Zero-pad the sample axis to a multiple of ``n_dev`` so the (S, L, A)
    tensor shards evenly over the mesh data axis.

    The padding rows are all-zero BY CONSTRUCTION — the additive identity
    of the cohort sum — so they cannot leak into the cohort tensor
    (locked by ``tests/unit/test_sec_aggregate.py``: non-divisible sample
    counts must equal the plain ``np.sum`` over the real rows exactly).
    """
    s = sample_counts.shape[0]
    pad = (-s) % n_dev
    if not pad:
        return sample_counts
    return np.concatenate(
        [sample_counts,
         np.zeros((pad, *sample_counts.shape[1:]), sample_counts.dtype)],
        axis=0)


def aggregate_on_mesh(sample_counts: np.ndarray, mesh: Mesh) -> np.ndarray:
    """(S, L, A) per-sample count tensors -> (L, A) cohort sum via psum.

    Samples shard over the mesh data axis (padded to a multiple); the
    result is replicated on every device. The device-put + replicated
    mesh sum is the shared :func:`parallel.mesh.mesh_sum_leading` — one
    reduction for this and the multi-host cohort aggregation.
    """
    from variantcalling_tpu.parallel.mesh import mesh_sum_leading

    sample_counts = pad_samples_to_devices(np.asarray(sample_counts),
                                           mesh.shape[DATA_AXIS])
    return mesh_sum_leading(mesh, sample_counts, "sec.aggregate_on_mesh")
