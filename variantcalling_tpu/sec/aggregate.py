"""Cohort count aggregation as a mesh all-reduce (BASELINE config 5).

The reference's SEC training walks per-sample files on one box; at pod
scale the natural layout is samples sharded across devices and the cohort
noise tensor produced by one ``psum`` over the sample axis — ICI-resident,
no host gather. ``aggregate_on_mesh`` is that program: a shard_map whose
per-device body sums its local sample slab and psums across the mesh.
Multi-HOST cohorts (each host holding its own sample files) go through
``parallel.distributed.aggregate_counts_across_hosts``, the same psum
over a global mesh spanning every host's devices.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from variantcalling_tpu.parallel.mesh import DATA_AXIS


def aggregate_on_mesh(sample_counts: np.ndarray, mesh: Mesh) -> np.ndarray:
    """(S, L, A) per-sample count tensors -> (L, A) cohort sum via psum.

    Samples shard over the mesh data axis (padded to a multiple); the
    result is replicated on every device.
    """
    s = sample_counts.shape[0]
    n_dev = mesh.shape[DATA_AXIS]
    pad = (-s) % n_dev
    if pad:
        sample_counts = np.concatenate(
            [sample_counts, np.zeros((pad, *sample_counts.shape[1:]), sample_counts.dtype)], axis=0
        )
    arr = jax.device_put(jnp.asarray(sample_counts), NamedSharding(mesh, P(DATA_AXIS, None, None)))

    @jax.jit
    def reduce(x):
        return jax.lax.with_sharding_constraint(
            jnp.sum(x, axis=0, dtype=jnp.float32), NamedSharding(mesh, P(None, None))
        )

    with mesh:
        out = reduce(arr)
    return np.asarray(out)
