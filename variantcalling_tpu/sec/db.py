"""SEC systematic-error database: per-locus cohort allele-count distributions.

Re-derivation of the reference's SEC model (missing ugbio_filtering.sec
submodule; statistical basis = the multinomial likelihood machinery the
reference keeps in ugvc/utils/stats_utils.py:12-70, orphaned test resource
names: "merge_conditional_allele_distributions"). The DB stores, for every
known-noisy locus, the cohort-aggregated allele-count distribution observed
in samples that do NOT carry a real variant there — the noise fingerprint.

Layout is columnar and device-ready: packed (contig_idx << 40 | pos) int64
locus keys, an (L, A) count tensor (A = ref + 3 alt slots + other), and
sample counts — so correction scores millions of loci as one batched
kernel, and cohort building is an all-reduce over per-sample tensors
(BASELINE config 5).
"""

from __future__ import annotations

from dataclasses import dataclass

import h5py
import numpy as np

N_ALLELE_SLOTS = 5  # ref, alt1, alt2, alt3, other


@dataclass
class SecDb:
    contigs: list[str]  # contig name per index used in keys
    keys: np.ndarray  # int64 (L,) sorted packed (contig_idx << 40) | pos(1-based)
    counts: np.ndarray  # float32 (L, N_ALLELE_SLOTS) cohort noise allele counts
    n_samples: int

    def __len__(self) -> int:
        return len(self.keys)

    def contig_index(self) -> dict[str, int]:
        return {c: i for i, c in enumerate(self.contigs)}

    def lookup(self, chrom: np.ndarray, pos: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """(hit mask, row index) for (chrom, 1-based pos) arrays."""
        cmap = self.contig_index()
        cidx = np.fromiter((cmap.get(c, -1) for c in chrom), dtype=np.int64, count=len(chrom))
        key = (cidx << 40) | np.asarray(pos, dtype=np.int64)
        if len(self.keys) == 0:
            return np.zeros(len(chrom), dtype=bool), np.zeros(len(chrom), dtype=np.int64)
        loc = np.minimum(np.searchsorted(self.keys, key), len(self.keys) - 1)
        hit = (self.keys[loc] == key) & (cidx >= 0)
        return hit, loc

    def save(self, path: str) -> None:
        with h5py.File(path, "w") as f:
            f.attrs["n_samples"] = self.n_samples
            dt = h5py.string_dtype()
            f.create_dataset("contigs", data=np.asarray(self.contigs, dtype=dt), dtype=dt)
            f.create_dataset("keys", data=self.keys)
            f.create_dataset("counts", data=self.counts)

    @staticmethod
    def load(path: str) -> "SecDb":
        with h5py.File(path, "r") as f:
            contigs = [c.decode() if isinstance(c, bytes) else str(c) for c in f["contigs"][()]]
            return SecDb(
                contigs=contigs,
                keys=f["keys"][()],
                counts=f["counts"][()],
                n_samples=int(f.attrs["n_samples"]),
            )


def pack_keys(contigs: list[str], chrom: np.ndarray, pos: np.ndarray) -> np.ndarray:
    cmap = {c: i for i, c in enumerate(contigs)}
    cidx = np.fromiter((cmap[c] for c in chrom), dtype=np.int64, count=len(chrom))
    return (cidx << 40) | np.asarray(pos, dtype=np.int64)


def merge_sample_counts(
    contigs: list[str],
    per_sample: list[tuple[np.ndarray, np.ndarray]],  # (keys, (l, A) counts) per sample
) -> SecDb:
    """Union of loci; summed counts — the host-side (DCN-scale) merge.

    Device-side cohort aggregation over a mesh lives in sec.aggregate;
    this entry point merges pre-reduced per-sample (or per-host) tables.
    """
    all_keys = np.unique(np.concatenate([k for k, _ in per_sample])) if per_sample else np.array([], np.int64)
    counts = np.zeros((len(all_keys), N_ALLELE_SLOTS), dtype=np.float32)
    for keys, c in per_sample:
        idx = np.searchsorted(all_keys, keys)
        np.add.at(counts, idx, c.astype(np.float32))
    return SecDb(contigs=list(contigs), keys=all_keys, counts=counts, n_samples=len(per_sample))
