"""SEC correction: score observed allele counts against the cohort noise DB.

For every callset variant at a DB locus the test is the batched multinomial
likelihood ratio (ops/stats, parity ugvc/utils/stats_utils.py:48-70): how
likely are the observed AD counts under the cohort noise distribution,
relative to their own best fit? High ratio -> the observation looks like
the systematic noise seen across the cohort -> the call is corrected
(FILTER gains SEC, report-side re-filtering per report_utils.py:71-75).
One jitted kernel scores the whole callset; no per-locus scipy.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from variantcalling_tpu.ops.stats import multinomial_log_pmf, correct_multinomial_frequencies
from variantcalling_tpu.sec.db import N_ALLELE_SLOTS, SecDb

DEFAULT_NOISE_RATIO = 0.1  # ratio above this -> noise-consistent -> SEC


@jax.jit
def noise_likelihood_ratio(observed: jnp.ndarray, noise_counts: jnp.ndarray) -> jnp.ndarray:
    """(N,) likelihood ratio of observed (N, A) counts under noise (N, A)."""
    p_noise = correct_multinomial_frequencies(noise_counts)
    log_l = multinomial_log_pmf(observed, p_noise)
    log_max = multinomial_log_pmf(observed, correct_multinomial_frequencies(observed))
    return jnp.exp(log_l - log_max)


def observed_allele_counts(table, max_alts: int = N_ALLELE_SLOTS - 2) -> np.ndarray:
    """(N, N_ALLELE_SLOTS) counts from FORMAT/AD: ref, alt1..alt3, other."""
    ad = table.format_numeric("AD")
    n = len(table)
    out = np.zeros((n, N_ALLELE_SLOTS), dtype=np.float32)
    if ad.shape[1] == 0:
        return out
    valid = np.where(ad >= 0, ad, 0.0)
    out[:, 0] = valid[:, 0] if ad.shape[1] > 0 else 0
    k = min(max_alts, ad.shape[1] - 1)
    if k > 0:
        out[:, 1 : 1 + k] = valid[:, 1 : 1 + k]
    if ad.shape[1] - 1 > max_alts:
        out[:, -1] = valid[:, 1 + max_alts :].sum(axis=1)
    return out


def correct_calls(
    table,
    db: SecDb,
    noise_ratio_threshold: float = DEFAULT_NOISE_RATIO,
) -> tuple[np.ndarray, np.ndarray]:
    """(is_sec bool per record, likelihood ratio float per record)."""
    hit, rows = db.lookup(np.asarray(table.chrom), table.pos)
    ratios = np.zeros(len(table), dtype=np.float32)
    if not hit.any() or len(db) == 0:
        return np.zeros(len(table), dtype=bool), ratios
    obs = observed_allele_counts(table)[hit]
    noise = db.counts[rows[hit]]
    r = np.asarray(noise_likelihood_ratio(jnp.asarray(obs), jnp.asarray(noise)))
    ratios[hit] = r
    is_sec = np.zeros(len(table), dtype=bool)
    is_sec[hit] = r > noise_ratio_threshold
    return is_sec, ratios
