"""SEC — systematic error correction: cohort noise DB + per-locus testing."""
