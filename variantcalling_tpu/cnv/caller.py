"""CNV calling from binned coverage: GC normalization + HMM segmentation.

Reference surface: the ugbio_cnv package (setup.py:4-8) — the reference
calls CNVs with external R/py tools (cn.mops, cnvpytor envs at
setup/other_envs/cnmops.yml). This module is the TPU-native equivalent
over the coverage pipeline's binned depth (pipelines/coverage_analysis
windows): median/GC normalization to log2 ratios, then a copy-number HMM
whose forward pass and Viterbi backtrace run as ``lax.scan`` device
kernels — segmentation of a whole genome's bins is one jitted program.

States: copy number 0..4 (del0, del1, neutral, dup3, dup4); emissions are
Gaussian in log2-ratio space centered at log2(cn/2) (cn=0 floored).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

import jax
import jax.numpy as jnp

COPY_STATES = np.array([0, 1, 2, 3, 4])
_LOG2_FLOOR = -3.0  # log2 ratio assigned to cn=0 (avoid -inf)


def state_means() -> np.ndarray:
    with np.errstate(divide="ignore"):
        m = np.log2(np.maximum(COPY_STATES, 1e-9) / 2.0)
    return np.maximum(m, _LOG2_FLOOR)


def normalize_coverage(
    depth: np.ndarray, gc: np.ndarray | None = None, n_gc_bins: int = 20
) -> np.ndarray:
    """Binned depth -> log2 ratio vs autosomal median, GC-corrected.

    GC correction: each GC-content stratum is scaled to the global median
    (the LOESS-free rolling-median correction cn.mops-family tools use).
    """
    depth = np.asarray(depth, dtype=np.float64)
    med = np.median(depth[depth > 0]) if (depth > 0).any() else 1.0
    corrected = depth.astype(np.float64)
    if gc is not None:
        gc_bin = np.clip((np.asarray(gc) * n_gc_bins).astype(int), 0, n_gc_bins - 1)
        for b in range(n_gc_bins):
            m = gc_bin == b
            if m.sum() >= 10:
                stratum_med = np.median(corrected[m][corrected[m] > 0]) if (corrected[m] > 0).any() else med
                if stratum_med > 0:
                    corrected[m] *= med / stratum_med
    ratio = corrected / max(med, 1e-9)
    return np.log2(np.maximum(ratio, 2.0**_LOG2_FLOOR)).astype(np.float32)


def viterbi_segment(
    log2_ratio: np.ndarray,
    sigma: float = 0.35,
    p_stay: float = 0.999,
) -> np.ndarray:
    """Most likely copy-number state per bin (device Viterbi over lax.scan)."""
    means = jnp.asarray(state_means(), dtype=jnp.float32)
    k = len(COPY_STATES)
    obs = jnp.asarray(log2_ratio, dtype=jnp.float32)
    log_trans = jnp.log(
        jnp.where(jnp.eye(k, dtype=bool), p_stay, (1.0 - p_stay) / (k - 1))
    ).astype(jnp.float32)

    def emission(o):
        return -0.5 * ((o - means) / sigma) ** 2  # (K,)

    def fwd_step(delta, o):
        # delta: (K,) best log prob ending in each state
        cand = delta[:, None] + log_trans  # (K_prev, K)
        best_prev = jnp.argmax(cand, axis=0)  # (K,)
        delta_new = jnp.max(cand, axis=0) + emission(o)
        return delta_new, best_prev

    delta0 = emission(obs[0]) + jnp.log(jnp.full((k,), 1.0 / k))
    delta_t, backptr = jax.lax.scan(fwd_step, delta0, obs[1:])

    def back_step(state, ptr):
        prev = ptr[state]
        return prev, prev

    last = jnp.argmax(delta_t)
    _, states_rev = jax.lax.scan(back_step, last, backptr[::-1])
    states = jnp.concatenate([states_rev[::-1], jnp.array([last])])
    return np.asarray(states, dtype=np.int32)


@dataclass
class Segment:
    chrom: str
    start: int  # 0-based bin-aligned
    end: int
    copy_number: int
    n_bins: int
    mean_log2: float


def states_to_segments(
    states: np.ndarray, log2_ratio: np.ndarray, chrom: str, bin_size: int, min_bins: int = 3
) -> list[Segment]:
    """Run-length merge of per-bin states into CNV segments (neutral dropped)."""
    segs: list[Segment] = []
    n = len(states)
    i = 0
    while i < n:
        j = i
        while j < n and states[j] == states[i]:
            j += 1
        cn = int(COPY_STATES[states[i]])
        if cn != 2 and (j - i) >= min_bins:
            segs.append(
                Segment(
                    chrom=chrom,
                    start=i * bin_size,
                    end=j * bin_size,
                    copy_number=cn,
                    n_bins=j - i,
                    mean_log2=float(np.mean(log2_ratio[i:j])),
                )
            )
        i = j
    return segs


def call_cnvs(
    depth_per_contig: dict[str, np.ndarray],
    bin_size: int,
    gc_per_contig: dict[str, np.ndarray] | None = None,
    sigma: float = 0.35,
    p_stay: float = 0.999,
    min_bins: int = 3,
) -> list[Segment]:
    """End-to-end: normalize (jointly) then segment each contig."""
    names = list(depth_per_contig)
    all_depth = np.concatenate([depth_per_contig[c] for c in names])
    all_gc = (
        np.concatenate([gc_per_contig[c] for c in names]) if gc_per_contig else None
    )
    log2 = normalize_coverage(all_depth, all_gc)
    segs: list[Segment] = []
    off = 0
    for c in names:
        n = len(depth_per_contig[c])
        lr = log2[off : off + n]
        states = viterbi_segment(lr, sigma=sigma, p_stay=p_stay)
        segs.extend(states_to_segments(states, lr, c, bin_size, min_bins))
        off += n
    return segs
