"""CLI dispatch: ``python -m variantcalling_tpu <tool> <args>`` (or ``vctpu <tool>``).

Mirrors the reference's ugvc CLI surface (ugvc/__main__.py:43-105): each tool
is a module exposing ``run(argv)`` with its own argparse parser; tools are
lazily imported so the CLI stays fast and optional heavy deps stay optional.
"""

from __future__ import annotations

import importlib
import sys

# tool name -> module path (module must expose run(argv))
TOOLS: dict[str, str] = {
    "knobs": "variantcalling_tpu.knobs",
    "obs": "variantcalling_tpu.obs.cli",
    "serve": "variantcalling_tpu.serve.cli",
    "merge-ranks": "variantcalling_tpu.parallel.rank_plan",
    "filter_variants_pipeline": "variantcalling_tpu.pipelines.filter_variants",
    "train_models_pipeline": "variantcalling_tpu.pipelines.train_models",
    "training_prep_pipeline": "variantcalling_tpu.pipelines.training_prep",
    "run_comparison_pipeline": "variantcalling_tpu.pipelines.run_comparison",
    "evaluate_concordance": "variantcalling_tpu.pipelines.evaluate_concordance",
    "coverage_analysis": "variantcalling_tpu.pipelines.coverage_analysis",
    "correct_systematic_errors": "variantcalling_tpu.pipelines.sec.correct_systematic_errors",
    "sec_training": "variantcalling_tpu.pipelines.sec.sec_training",
    "sec_validation": "variantcalling_tpu.pipelines.sec.sec_validation",
    "assess_sec_concordance": "variantcalling_tpu.pipelines.sec.assess_sec_concordance",
    "concat_methyldackel_csvs": "variantcalling_tpu.pipelines.methylation.concat_methyldackel_csvs",
    "process_mbias": "variantcalling_tpu.pipelines.methylation.process_mbias",
    "process_merge_context": "variantcalling_tpu.pipelines.methylation.process_merge_context",
    "process_merge_context_no_cp_g": "variantcalling_tpu.pipelines.methylation.process_merge_context_no_cp_g",
    "process_per_read": "variantcalling_tpu.pipelines.methylation.process_per_read",
    "cloud_sync": "variantcalling_tpu.pipelines.misc.cloud_sync",
    "sorter_to_h5": "variantcalling_tpu.pipelines.misc.sorter_to_h5",
    "sorter_stats_to_mean_coverage": "variantcalling_tpu.pipelines.misc.sorter_stats_to_mean_coverage",
    "collect_existing_metrics": "variantcalling_tpu.pipelines.misc.collect_existing_metrics",
    "convert_h5_to_json": "variantcalling_tpu.pipelines.misc.convert_h5_to_json",
    "annotate_contig": "variantcalling_tpu.pipelines.vcfbed.annotate_contig",
    "intersect_bed_regions": "variantcalling_tpu.pipelines.vcfbed.intersect_bed_regions",
    "find_runs_bed": "variantcalling_tpu.pipelines.misc.find_runs_bed",
    "index_vcf_file": "variantcalling_tpu.pipelines.misc.index_vcf_file",
    "remove_vcf_duplicates": "variantcalling_tpu.pipelines.misc.remove_vcf_duplicates",
    "remove_empty_files": "variantcalling_tpu.pipelines.misc.remove_empty_files",
    "correct_genotypes_by_imputation": "variantcalling_tpu.pipelines.correct_genotypes_by_imputation",
    "convert_haploid_regions": "variantcalling_tpu.pipelines.convert_haploid_regions",
    "compress_gvcf": "variantcalling_tpu.pipelines.compress_gvcf",
    "cleanup_gvcf_before_calling": "variantcalling_tpu.pipelines.cleanup_gvcf_before_calling",
    "gvcf_hcr": "variantcalling_tpu.pipelines.gvcf_hcr",
    "denovo_recalibrated_qualities": "variantcalling_tpu.pipelines.denovo_recalibrated_qualities",
    "quick_fingerprinting": "variantcalling_tpu.pipelines.quick_fingerprinting",
    "sv_stats_collect": "variantcalling_tpu.pipelines.sv_stats_collect",
    "run_no_gt_report": "variantcalling_tpu.pipelines.run_no_gt_report",
    "vcfeval_flavors": "variantcalling_tpu.pipelines.vcfeval_flavors",
    "create_var_report": "variantcalling_tpu.pipelines.create_var_report",
    "create_sv_report": "variantcalling_tpu.pipelines.create_sv_report",
    "create_qc_report": "variantcalling_tpu.pipelines.create_qc_report",
    "joint_calling_report": "variantcalling_tpu.pipelines.joint_calling_report",
    "substitution_error_rate_report": "variantcalling_tpu.pipelines.substitution_error_rate_report",
    "import_metrics": "variantcalling_tpu.pipelines.import_metrics",
    "cnv_calling": "variantcalling_tpu.pipelines.cnv_calling",
    "srsnv_training": "variantcalling_tpu.pipelines.srsnv.srsnv_training",
    "srsnv_inference": "variantcalling_tpu.pipelines.srsnv.srsnv_inference",
    "mrd_analysis": "variantcalling_tpu.pipelines.mrd_analysis",
    "ppmseq_qc": "variantcalling_tpu.pipelines.ppmseq_qc",
    "create_somatic_gt_file": "variantcalling_tpu.pipelines.create_somatic_gt_file",
    "run_somatic_comparison_and_graphs": "variantcalling_tpu.pipelines.run_somatic_comparison_and_graphs",
    "train_dan": "variantcalling_tpu.pipelines.train_dan",
    "report_wo_gt": "variantcalling_tpu.pipelines.report_wo_gt",
    "mrd_data_analysis": "variantcalling_tpu.pipelines.mrd_data_analysis",
    "detailed_var_report": "variantcalling_tpu.pipelines.detailed_var_report",
    "find_adapter_coords": "variantcalling_tpu.pipelines.find_adapter_coords",
    "add_ml_tags_bam": "variantcalling_tpu.pipelines.add_ml_tags_bam",
    "collect_hpol_table": "variantcalling_tpu.pipelines.collect_hpol_table",
    "calibrate_bridging_snvs": "variantcalling_tpu.pipelines.calibrate_bridging_snvs",
    "training_set_consistency_check": "variantcalling_tpu.pipelines.training_set_consistency_check",
    "train_lib_prep_recalibration_model": "variantcalling_tpu.pipelines.lpr.train_lib_prep_recalibration_model",
    "filter_vcf_with_lib_prep_recalibration_model": (
        "variantcalling_tpu.pipelines.lpr.filter_vcf_with_lib_prep_recalibration_model"
    ),
}

_LOGO = "variantcalling-tpu (vctpu) — TPU-native variant-calling post-processing"


def main(argv: list[str] | None = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in {"-h", "--help"}:
        print(_LOGO)
        print("usage: python -m variantcalling_tpu <tool> [tool args]\n\ntools:")
        for name in sorted(TOOLS):
            print(f"  {name}")
        return 0
    tool = argv[0]
    if tool not in TOOLS:
        print(f"unknown tool: {tool!r}; run with --help for the tool list", file=sys.stderr)
        return 2
    # configuration errors (EngineError — e.g. a malformed VCTPU_* knob
    # parsed during tool import or startup) exit 2 with the message, not
    # a traceback: the knob-registry contract at the dispatch level
    from variantcalling_tpu.engine import EngineError

    try:
        module = importlib.import_module(TOOLS[tool])
    except ModuleNotFoundError as e:
        print(f"tool {tool!r} is not available yet: {e}", file=sys.stderr)
        return 3
    except EngineError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    # unknown VCTPU_* variables are almost always typos of real knobs —
    # warn (with a closest-match suggestion) before the tool runs, so
    # VCTPU_FOERST_STRATEGY=wide can no longer be silently ignored
    from variantcalling_tpu import knobs

    knobs.warn_unknown_env()
    try:
        # multi-host launch: VCTPU_COORDINATOR/NUM_PROCESSES/PROCESS_ID in
        # the env turn any tool into one rank of a global mesh
        # (parallel/distributed). Gated on the env so plain runs keep the
        # lazy-import fast path.
        if knobs.get_str("VCTPU_COORDINATOR") or knobs.get_bool("VCTPU_AUTO_DISTRIBUTED"):
            from variantcalling_tpu.parallel.distributed import init_from_env

            init_from_env()
        # per-file CLI invocations must not re-pay jit compiles: persist XLA
        # executables across processes (~/.cache/vctpu/xla, VCTPU_COMPILE_CACHE
        # overrides, empty disables)
        from variantcalling_tpu.utils.compile_cache import enable_persistent_cache

        enable_persistent_cache()
        result = module.run(argv[1:])
    except EngineError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    # tools may return rich results (e.g. vcfeval_flavors' rows); only
    # int-like returns are exit codes
    return result if isinstance(result, int) else 0


if __name__ == "__main__":
    sys.exit(main())
