"""Dependency-free bigWig reader/writer (UCSC bbiFile format, little-endian).

The reference shells out to UCSC ``bedGraphToBigWig`` for export
(/root/reference/ugvc/pipelines/coverage_analysis.py:686-714) and reads
coverage back via pyBigWig (:745-786, and per-variant coverage annotation in
run_comparison, docs/run_comparison_pipeline.md:57-60). Neither binary nor
pyBigWig is in this image, so both directions are implemented natively:

- :func:`write_bigwig` — per-contig value arrays -> .bw with a chromosome
  B+ tree, bedGraph-typed data sections (run-length encoded) and a two-level
  R-tree index. Sections are zlib-compressed like the UCSC writer.
- :class:`BigWigReader` — header/chrom-tree/R-tree parser serving
  ``values(chrom, start, end)`` (NaN where uncovered) and ``chroms()``,
  the pyBigWig surface the reference uses. Handles compressed and
  uncompressed sections, all three WIG section types.

Zoom levels are written as zero (valid per the spec; readers fall back to
full-resolution data for summaries).
"""

from __future__ import annotations

import struct
import zlib

import numpy as np

BIGWIG_MAGIC = 0x888FFC26
CHROM_TREE_MAGIC = 0x78CA8C91
RTREE_MAGIC = 0x2468ACE0

_SECTION_ITEMS = 1024  # bedGraph items per data section (fits u16 itemCount)


# ---------------------------------------------------------------------------
# writer
# ---------------------------------------------------------------------------

def _runlength(values: np.ndarray) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(starts, ends, vals) runs of equal value; zero runs are kept (bedGraph
    emits them, matching `samtools depth -a` semantics in the reference)."""
    v = np.asarray(values, dtype=np.float32)
    if len(v) == 0:
        return np.empty(0, np.int64), np.empty(0, np.int64), np.empty(0, np.float32)
    change = np.nonzero(v[1:] != v[:-1])[0] + 1
    starts = np.concatenate([[0], change])
    ends = np.concatenate([change, [len(v)]])
    return starts, ends, v[starts]


def write_bigwig(path: str, chrom_values: dict[str, np.ndarray],
                 chrom_sizes: dict[str, int] | None = None, compress: bool = True) -> None:
    """Write per-base value arrays as a bigWig file.

    ``chrom_values``: contig -> float array (per-base, position 0-based).
    ``chrom_sizes`` defaults to the array lengths.
    """
    chroms = list(chrom_values)
    sizes = {c: int(chrom_sizes[c]) if chrom_sizes else len(chrom_values[c]) for c in chroms}
    key_size = max([len(c) for c in chroms] or [1])

    sections = []  # (chrom_id, start, end, payload_bytes)
    valid = 0
    vmin, vmax, vsum, vsumsq = np.inf, -np.inf, 0.0, 0.0
    for cid, c in enumerate(chroms):
        starts, ends, vals = _runlength(chrom_values[c])
        if len(vals):
            # every emitted base — including zero runs — is "covered" data
            valid += int((ends - starts).sum())
            vmin = min(vmin, float(vals.min()))
            vmax = max(vmax, float(vals.max()))
            w = (ends - starts).astype(np.float64)
            vsum += float((vals * w).sum())
            vsumsq += float((vals.astype(np.float64) ** 2 * w).sum())
        for lo in range(0, len(starts), _SECTION_ITEMS):
            hi = min(lo + _SECTION_ITEMS, len(starts))
            s, e, v = starts[lo:hi], ends[lo:hi], vals[lo:hi]
            head = struct.pack("<IIIIIBBH", cid, int(s[0]), int(e[-1]), 0, 0, 1, 0, hi - lo)
            items = np.empty((hi - lo, 3), dtype=np.uint32)
            items[:, 0] = s
            items[:, 1] = e
            items[:, 2] = v.view(np.uint32) if v.dtype == np.float32 else \
                v.astype(np.float32).view(np.uint32)
            sections.append((cid, int(s[0]), int(e[-1]), head + items.tobytes()))
    if not np.isfinite(vmin):
        vmin = vmax = 0.0

    uncompress_buf = max((len(p) for _, _, _, p in sections), default=0)
    payloads = [zlib.compress(p) if compress else p for _, _, _, p in sections]

    # ---- layout ----
    n_chroms = len(chroms)
    header_size = 64
    chrom_tree_offset = header_size  # no zoom headers (zoomLevels=0)
    chrom_tree_size = 32 + 4 + (key_size + 8) * n_chroms
    total_summary_offset = chrom_tree_offset + chrom_tree_size
    full_data_offset = total_summary_offset + 40
    data_sizes = [len(p) for p in payloads]
    data_start = full_data_offset + 8
    offsets = np.concatenate([[0], np.cumsum(data_sizes)])[:-1] + data_start
    full_index_offset = data_start + sum(data_sizes)

    with open(path, "wb") as fh:
        fh.write(
            struct.pack(
                "<IHHQQQHHQQIQ",
                BIGWIG_MAGIC, 4, 0,
                chrom_tree_offset, full_data_offset, full_index_offset,
                0, 0, 0, total_summary_offset,
                uncompress_buf if compress else 0, 0,
            )
        )
        # chromosome B+ tree: one leaf node
        fh.write(struct.pack("<IIIIQQ", CHROM_TREE_MAGIC, max(n_chroms, 1), key_size, 8,
                             n_chroms, 0))
        fh.write(struct.pack("<BBH", 1, 0, n_chroms))
        for cid, c in enumerate(chroms):
            fh.write(c.encode().ljust(key_size, b"\x00"))
            fh.write(struct.pack("<II", cid, sizes[c]))
        fh.write(struct.pack("<Qdddd", valid, vmin, vmax, vsum, vsumsq))
        fh.write(struct.pack("<Q", len(sections)))
        for p in payloads:
            fh.write(p)
        _write_rtree(fh, sections, offsets, data_sizes, full_index_offset)


def _write_rtree(fh, sections, offsets, data_sizes, index_offset) -> None:
    """Two-level R-tree: one root over leaf nodes of <=256 items."""
    block = 256
    n = len(sections)
    if n:
        s_cid, s_start = sections[0][0], sections[0][1]
        e_cid, e_end = sections[-1][0], sections[-1][2]
    else:
        s_cid = s_start = e_cid = e_end = 0
    end_file = int(offsets[-1] + data_sizes[-1]) if n else index_offset
    fh.write(struct.pack("<IIQIIIIQII", RTREE_MAGIC, block, n,
                         s_cid, s_start, e_cid, e_end, end_file, _SECTION_ITEMS, 0))
    groups = [list(range(lo, min(lo + block, n))) for lo in range(0, n, block)] or [[]]
    if len(groups) == 1:
        _write_rtree_leaf(fh, groups[0], sections, offsets, data_sizes)
        return
    # root (internal) node, then leaves at computed offsets
    root_size = 4 + 24 * len(groups)
    leaf_sizes = [4 + 32 * len(g) for g in groups]
    leaf_offs = np.concatenate([[0], np.cumsum(leaf_sizes)])[:-1] + index_offset + 48 + root_size
    fh.write(struct.pack("<BBH", 0, 0, len(groups)))
    for g, off in zip(groups, leaf_offs):
        a, b = sections[g[0]], sections[g[-1]]
        fh.write(struct.pack("<IIIIQ", a[0], a[1], b[0], b[2], int(off)))
    for g in groups:
        _write_rtree_leaf(fh, g, sections, offsets, data_sizes)


def _write_rtree_leaf(fh, idxs, sections, offsets, data_sizes) -> None:
    fh.write(struct.pack("<BBH", 1, 0, len(idxs)))
    for i in idxs:
        cid, start, end, _ = sections[i]
        fh.write(struct.pack("<IIIIQQ", cid, start, cid, end, int(offsets[i]), data_sizes[i]))


# ---------------------------------------------------------------------------
# reader
# ---------------------------------------------------------------------------

class BigWigReader:
    """Minimal pyBigWig-compatible reader: chroms() + values()."""

    def __init__(self, path: str):
        import mmap

        self.path = path
        self._fh = open(path, "rb")
        # mmap: block reads stay page-backed, so multi-GB WGS tracks never
        # fully materialize in RAM (only R-tree-hit pages fault in)
        self._data = mmap.mmap(self._fh.fileno(), 0, access=mmap.ACCESS_READ)
        magic, version, zooms, chrom_off, data_off, index_off, _fc, _dfc, _auto, \
            _summ, self._uncomp, _res = struct.unpack_from("<IHHQQQHHQQIQ", self._data, 0)
        if magic != BIGWIG_MAGIC:
            raise ValueError(f"not a little-endian bigWig file: {path}")
        self._index_off = index_off
        self._chrom_ids: dict[str, int] = {}
        self._chrom_sizes: dict[str, int] = {}
        self._read_chrom_tree(chrom_off)
        self._names = {v: k for k, v in self._chrom_ids.items()}

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()
        return False

    def close(self) -> None:
        try:
            self._data.close()
            self._fh.close()
        except (OSError, ValueError):
            pass

    def chroms(self, chrom: str | None = None):
        if chrom is not None:
            return self._chrom_sizes.get(chrom)
        return dict(self._chrom_sizes)

    def _read_chrom_tree(self, off: int) -> None:
        magic, _block, key_size, _val, _count, _res = struct.unpack_from("<IIIIQQ", self._data, off)
        if magic != CHROM_TREE_MAGIC:
            raise ValueError("bad chromosome tree")
        self._walk_chrom_node(off + 32, key_size)

    def _walk_chrom_node(self, off: int, key_size: int) -> None:
        is_leaf, _res, count = struct.unpack_from("<BBH", self._data, off)
        p = off + 4
        for _ in range(count):
            key = self._data[p : p + key_size].rstrip(b"\x00").decode()
            if is_leaf:
                cid, csize = struct.unpack_from("<II", self._data, p + key_size)
                self._chrom_ids[key] = cid
                self._chrom_sizes[key] = csize
                p += key_size + 8
            else:
                (child,) = struct.unpack_from("<Q", self._data, p + key_size)
                self._walk_chrom_node(child, key_size)
                p += key_size + 8

    def _overlapping_blocks(self, cid: int, start: int, end: int) -> list[tuple[int, int]]:
        out: list[tuple[int, int]] = []
        self._walk_rtree(self._index_off + 48, cid, start, end, out)
        return out

    def _walk_rtree(self, off: int, cid: int, start: int, end: int, out: list) -> None:
        is_leaf, _res, count = struct.unpack_from("<BBH", self._data, off)
        p = off + 4
        for _ in range(count):
            if is_leaf:
                scid, s, ecid, e, doff, dsize = struct.unpack_from("<IIIIQQ", self._data, p)
                p += 32
            else:
                scid, s, ecid, e, doff = struct.unpack_from("<IIIIQ", self._data, p)
                dsize = None
                p += 24
            if (scid, s) > (cid, end) or (ecid, e) < (cid, start):
                # no overlap with [cid:start, cid:end]
                if scid > cid or (scid == cid and s >= end):
                    break
                continue
            if is_leaf:
                out.append((doff, dsize))
            else:
                self._walk_rtree(doff, cid, start, end, out)

    def _section_items(self, payload: bytes):
        """Yield (start, end, value) from one WIG data section."""
        chrom_id, c_start, _c_end, step, span, typ, _res, n = struct.unpack_from(
            "<IIIIIBBH", payload, 0
        )
        body = payload[24:]
        if typ == 1:  # bedGraph
            arr = np.frombuffer(body, dtype="<u4", count=3 * n).reshape(n, 3)
            return chrom_id, arr[:, 0].astype(np.int64), arr[:, 1].astype(np.int64), \
                arr[:, 2].copy().view(np.float32)
        if typ == 2:  # varStep
            arr = np.frombuffer(body, dtype="<u4", count=2 * n).reshape(n, 2)
            s = arr[:, 0].astype(np.int64)
            return chrom_id, s, s + span, arr[:, 1].copy().view(np.float32)
        if typ == 3:  # fixedStep
            vals = np.frombuffer(body, dtype="<u4", count=n).copy().view(np.float32)
            s = c_start + step * np.arange(n, dtype=np.int64)
            return chrom_id, s, s + span, vals
        raise ValueError(f"unknown WIG section type {typ}")

    def values(self, chrom: str, start: int, end: int, numpy: bool = True) -> np.ndarray:
        """Per-base values over [start, end), NaN where uncovered (pyBigWig API)."""
        cid = self._chrom_ids.get(chrom)
        out = np.full(max(end - start, 0), np.nan, dtype=np.float64)
        if cid is None:
            return out if numpy else list(out)
        for doff, dsize in self._overlapping_blocks(cid, start, end):
            payload = self._data[doff : doff + dsize]
            if self._uncomp:
                payload = zlib.decompress(payload)
            scid, s, e, v = self._section_items(payload)
            if scid != cid:
                continue
            s2 = np.clip(s - start, 0, len(out))
            e2 = np.clip(e - start, 0, len(out))
            for a, b, val in zip(s2, e2, v):
                if b > a:
                    out[a:b] = val
        return out if numpy else list(out)

    def stats(self, chrom: str, start: int = 0, end: int | None = None,
              type: str = "mean") -> list:  # noqa: A002 — pyBigWig API name
        if end is None:
            end = self._chrom_sizes.get(chrom, 0)
        v = self.values(chrom, start, end)
        v = v[~np.isnan(v)]
        if len(v) == 0:
            return [None]
        fns = {"mean": np.mean, "min": np.min, "max": np.max, "sum": np.sum,
               "coverage": lambda x: len(x) / max(end - start, 1), "std": np.std}
        return [float(fns[type](v))]


def open_bigwig(path: str) -> BigWigReader:
    return BigWigReader(path)
