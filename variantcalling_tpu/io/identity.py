"""The ONE spelling of run identity — what makes scored bytes a pure
function of input.

Three subsystems must agree, byte for byte, on "same configuration":

- the resume journal (``io/journal.py``): already-committed chunks carry
  the old run's scores, so resuming under a different model/flags/engine
  would atomically commit a silently mixed output;
- the rank-segment markers (``parallel/rank_plan.py``): a completed
  segment is reusable only for the exact configuration that produced it;
- the chunk-result cache (``io/chunk_cache.py``): a cached rendered body
  may replay into a run only when every scoring-relevant input is
  identical — and MUST still replay when only scoring-IRRELEVANT knobs
  (io threads, obs, heartbeat cadence) changed, or the cache never hits.

Before this module each consumer spelled the identity dict inline; a
field added to one spelling but not another would silently weaken resume
safety or cache correctness. Now they all call :func:`scoring_fields` /
:func:`scoring_config` / :func:`resume_meta`, and
``tests/unit/test_chunk_cache.py`` locks the single-source-of-truth
property (the journal's config sub-dict IS the cache fingerprint input).

``input_signature`` lives here (journal re-exports it for callers of the
old spelling): the (size, mtime_ns) stat pair that pins a referenced
file without reading it.
"""

from __future__ import annotations

import hashlib
import json
import os


def input_signature(path: str) -> list[int]:
    """Cheap identity of a referenced file: (size, mtime_ns). Pins the
    file across runs without reading it — any rewrite, even same-size,
    bumps mtime_ns on every filesystem we target."""
    st = os.stat(path)
    return [int(st.st_size), int(st.st_mtime_ns)]


def file_sig(path: str | None) -> list | None:
    """``[abspath, size, mtime_ns]`` of an optional referenced file —
    the journal's ``_file_sig`` spelling, shared."""
    return None if not path else [os.path.abspath(path),
                                  *input_signature(path)]


def scoring_fields(args) -> dict:
    """The args-derived scoring identity: every flag/file that changes
    what TREE_SCORE/FILTER a record gets. Keys and value spellings are
    load-bearing — the journal header, segment markers and cache
    fingerprints are all built from this dict, so renaming a key
    invalidates (safely: recompute) every persisted identity."""
    return {
        "model_file": file_sig(getattr(args, "model_file", None)),
        "model_name": getattr(args, "model_name", None),
        "runs_file": file_sig(getattr(args, "runs_file", None)),
        "blacklist": file_sig(getattr(args, "blacklist", None)),
        "blacklist_cg_insertions": bool(
            getattr(args, "blacklist_cg_insertions", False)),
        "hpol": [int(v) for v in getattr(args, "hpol_filter_length_dist",
                                         [10, 10])],
        "flow_order": getattr(args, "flow_order", "TGCA"),
        "is_mutect": bool(getattr(args, "is_mutect", False)),
        "annotate_intervals": sorted(
            os.path.abspath(p)
            for p in (getattr(args, "annotate_intervals", None) or [])),
    }


def scoring_config(args, engine: str | None, forest_strategy: str | None,
                   mesh_devices: int, rank: int, ranks: int,
                   span: tuple | None = None,
                   model_family: str | None = None,
                   model_digest: str | None = None) -> dict:
    """The FULL scoring configuration: args-derived fields plus the
    resolved execution selection. This is the journal's ``config``
    sub-dict AND the chunk cache's fingerprint input — one object, so
    the two can never diverge.

    Why each execution field is identity (and io-threads/obs are NOT):

    - ``engine``/``forest_strategy``: every strategy is parity-tested
      byte-identical, but the identity pins the FULL scoring
      configuration (PR-2 contract) — provenance headers differ, and a
      parity regression must never be masked by a stale reuse;
    - ``mesh_devices``: record bytes are device-count-invariant but the
      provenance HEADER differs (``##vctpu_mesh=``), so a reuse across
      mesh layouts would splice mismatched provenance;
    - ``ranks``: the rank layout partitions the CHUNK SEQUENCE itself —
      a journal/segment/cache span written by rank r of n describes r's
      spans only (docs/scaleout.md). The deterministic cut rule means a
      rank's spans re-key identically across runs of the same layout.
    - ``span``: the elastic spelling of the same fact — an elastic
      worker's journal/segment describes exactly the absolute target
      interval ``[lo, hi)`` it was leased (``parallel/elastic.py``), so
      a journal handed off across a re-cut must pin the NEW interval.
      ``None`` for rank-fraction and single runs.
    - ``model_family``/``model_digest``: the resolved scoring family
      (forest|dan|threshold) and, for families whose weights don't pin
      through the model FILE alone (one pickle can hold several
      families under different names), a content digest of the selected
      model's weights. A family change — or a same-file weights change —
      restarts journals cleanly and can never cache-collide a DAN run
      into forest chunk bodies (or vice versa).
    """
    cfg = scoring_fields(args)
    cfg["engine"] = engine
    cfg["forest_strategy"] = forest_strategy
    cfg["mesh_devices"] = mesh_devices
    cfg["ranks"] = [rank, ranks]
    cfg["span"] = [int(span[0]), int(span[1])] if span is not None else None
    cfg["model_family"] = model_family
    cfg["model_digest"] = model_digest
    return cfg


def cache_identity(config: dict) -> dict:
    """The chunk cache's PARTITION-AGNOSTIC view of a scoring config:
    ``ranks``/``span`` removed. Record bytes are a pure function of the
    raw input span + the scoring configuration — never of which rank or
    elastic span rendered them — so a re-cut or stolen span must still
    warm-hit entries produced under the old partitioning
    (docs/caching.md). Resume journals and segment markers keep the
    partition fields: THEIR artifacts (chunk sequences, segments) really
    are partition-shaped."""
    cfg = dict(config)
    cfg.pop("ranks", None)
    cfg.pop("span", None)
    return cfg


def resume_meta(args, chunk_bytes: int, header_bytes: bytes,
                config: dict) -> dict:
    """The journal header identity: the exact input file + chunking +
    output header this partial was produced under, wrapping the shared
    scoring ``config``. Chunk boundaries are a pure function of (input
    bytes, chunk_bytes), so pinning both makes "skip the journaled
    prefix" byte-safe; the header length/CRC pin the provenance lines a
    resumed tail is spliced after."""
    import zlib

    return {
        "input": os.path.abspath(args.input_file),
        "input_sig": input_signature(args.input_file),
        "chunk_bytes": int(chunk_bytes),
        "header_len": len(header_bytes),
        "header_crc": zlib.crc32(header_bytes),
        "config": config,
    }


def fingerprint(config: dict) -> str:
    """Content address of a scoring configuration: sha256 over the
    canonical (sorted-keys, compact) JSON encoding. The cache composes
    this with the raw input span's CRC32 to key stored chunk results;
    canonical encoding means a dict built twice from the same inputs —
    or loaded back from a journal header — fingerprints identically."""
    blob = json.dumps(config, sort_keys=True,
                      separators=(",", ":")).encode("utf-8")
    return hashlib.sha256(blob).hexdigest()


def describe_mismatch(old: dict, new: dict, _prefix: str = "",
                      _limit: int = 6) -> str:
    """Human-readable field-level diff of two identity dicts — the
    resume/invalidation debuggability fix: production logs must say
    WHICH field invalidated a journal (or would invalidate a cache),
    not just that one did. Returns e.g.
    ``config.engine: journal='jit' run='native'``."""
    diffs: list[str] = []

    def walk(o, n, prefix):
        if len(diffs) >= _limit:
            return
        if isinstance(o, dict) and isinstance(n, dict):
            for k in sorted(set(o) | set(n)):
                walk(o.get(k), n.get(k),
                     f"{prefix}.{k}" if prefix else str(k))
            return
        if o != n:
            diffs.append(f"{prefix}: journal={o!r} run={n!r}")

    walk(old, new, _prefix)
    if not diffs:
        return "no field-level difference (type/shape change)"
    return "; ".join(diffs[:_limit])
