"""Minimal native BAM reader: header + alignment stream + depth accumulation.

The reference shells out to ``samtools depth -a -J -q -Q -l`` per contig
(coverage_analysis.py:653-683). This reader parses the BAM binary layout
directly (BGZF-deflated stream; spec: SAM v1 §4) and accumulates per-contig
depth as an int32 **difference array** — each aligned reference-consuming
run adds +1 at start and -1 at end, and the depth vector is one cumsum.
That turns the 3Gbp scan into array ops feeding the device reduction
kernels (ops/coverage), replacing the bedGraph text round-trip.

A C++ engine (variantcalling_tpu/native) can swap in for the hot parse
loop; this module is the readable spec and the fallback.
"""

from __future__ import annotations

import gzip
import struct
from dataclasses import dataclass

import numpy as np

# samtools depth default exclusion: UNMAP | SECONDARY | QCFAIL | DUP
EXCLUDE_FLAGS = 0x4 | 0x100 | 0x200 | 0x400

_CIGAR_OPS = "MIDNSHP=X"
_REF_CONSUME = {0, 2, 3, 7, 8}  # M, D, N, =, X
_COV_OPS_J = {0, 2, 7, 8}  # with -J: deletions covered, N never
_COV_OPS = {0, 7, 8}


@dataclass
class BamHeader:
    text: str
    references: list[str]
    lengths: dict[str, int]


@dataclass
class Alignment:
    ref_id: int
    pos: int  # 0-based leftmost
    mapq: int
    flag: int
    cigar: list[tuple[int, int]]  # (op, length)
    read_len: int
    qual: np.ndarray | None  # per-base phred or None
    seq: np.ndarray | None = None  # per-base code 0..3 A/C/G/T, 4 other (when decoded)
    tags: dict | None = None  # optional aux tags (when decode_tags)


def _parse_aux_tags(rec: bytes, off: int) -> dict:
    """BAM auxiliary fields (SAM spec §4.2.4): tag(2) type(1) value."""
    tags: dict = {}
    n = len(rec)
    while off + 3 <= n:
        tag = rec[off : off + 2].decode(errors="replace")
        typ = chr(rec[off + 2])
        off += 3
        if typ == "A":
            tags[tag] = chr(rec[off]); off += 1
        elif typ in "cC":
            tags[tag] = rec[off] if typ == "C" else struct.unpack_from("<b", rec, off)[0]; off += 1
        elif typ in "sS":
            tags[tag] = struct.unpack_from("<h" if typ == "s" else "<H", rec, off)[0]; off += 2
        elif typ in "iI":
            tags[tag] = struct.unpack_from("<i" if typ == "i" else "<I", rec, off)[0]; off += 4
        elif typ == "f":
            tags[tag] = struct.unpack_from("<f", rec, off)[0]; off += 4
        elif typ in "ZH":
            end = rec.index(b"\x00", off)
            tags[tag] = rec[off:end].decode(errors="replace"); off = end + 1
        elif typ == "B":
            sub = chr(rec[off]); (cnt,) = struct.unpack_from("<I", rec, off + 1); off += 5
            size = {"c": 1, "C": 1, "s": 2, "S": 2, "i": 4, "I": 4, "f": 4}[sub]
            fmt = {"c": "<b", "C": "<B", "s": "<h", "S": "<H", "i": "<i", "I": "<I", "f": "<f"}[sub]
            tags[tag] = [struct.unpack_from(fmt, rec, off + j * size)[0] for j in range(cnt)]
            off += cnt * size
        else:  # unknown type code: cannot continue safely
            break
    return tags


# BAM 4-bit base nibble -> 0..3 ACGT, 4 anything else ('=ACMGRSVTWYHKDBN')
_NIBBLE_TO_CODE = np.full(16, 4, dtype=np.uint8)
for _nib, _code in ((1, 0), (2, 1), (4, 2), (8, 3)):
    _NIBBLE_TO_CODE[_nib] = _code


def _read_exact(fh, n: int) -> bytes:
    buf = fh.read(n)
    if len(buf) != n:
        raise EOFError("truncated BAM")
    return buf


class BamReader:
    def __init__(self, path: str, decode_seq: bool = False, decode_tags: bool = False):
        self._decode_seq = decode_seq
        self._decode_tags = decode_tags
        self._fh = gzip.open(path, "rb")  # BGZF is valid multi-member gzip
        magic = _read_exact(self._fh, 4)
        if magic != b"BAM\x01":
            raise ValueError(f"{path}: not a BAM file")
        (l_text,) = struct.unpack("<i", _read_exact(self._fh, 4))
        text = _read_exact(self._fh, l_text).rstrip(b"\x00").decode(errors="replace")
        (n_ref,) = struct.unpack("<i", _read_exact(self._fh, 4))
        refs: list[str] = []
        lengths: dict[str, int] = {}
        for _ in range(n_ref):
            (l_name,) = struct.unpack("<i", _read_exact(self._fh, 4))
            name = _read_exact(self._fh, l_name)[:-1].decode()
            (l_ref,) = struct.unpack("<i", _read_exact(self._fh, 4))
            refs.append(name)
            lengths[name] = l_ref
        self.header = BamHeader(text, refs, lengths)

    def __iter__(self):
        while True:
            head = self._fh.read(4)
            if len(head) < 4:
                return
            (block_size,) = struct.unpack("<i", head)
            rec = _read_exact(self._fh, block_size)
            ref_id, pos, lrn_mq_bin, flag_nc, l_seq, _, _, _ = struct.unpack("<iiIIiiii", rec[:32])
            l_read_name = lrn_mq_bin & 0xFF
            mapq = (lrn_mq_bin >> 8) & 0xFF
            n_cigar = flag_nc & 0xFFFF
            flag = flag_nc >> 16
            off = 32 + l_read_name
            cigar_raw = np.frombuffer(rec, dtype="<u4", count=n_cigar, offset=off)
            off += 4 * n_cigar
            seq_bytes = (l_seq + 1) // 2
            seq = None
            if self._decode_seq and l_seq:
                packed = np.frombuffer(rec, dtype=np.uint8, count=seq_bytes, offset=off)
                nibbles = np.empty(seq_bytes * 2, dtype=np.uint8)
                nibbles[0::2] = packed >> 4
                nibbles[1::2] = packed & 0xF
                seq = _NIBBLE_TO_CODE[nibbles[:l_seq]]
            off += seq_bytes
            qual = np.frombuffer(rec, dtype=np.uint8, count=l_seq, offset=off) if l_seq else None
            off += l_seq
            tags = _parse_aux_tags(rec, off) if self._decode_tags else None
            cigar = [(int(c & 0xF), int(c >> 4)) for c in cigar_raw]
            yield Alignment(ref_id, pos, mapq, flag, cigar, l_seq, qual, seq, tags)

    def close(self) -> None:
        self._fh.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def depth_diff_arrays(
    path: str,
    min_bq: int = 0,
    min_mapq: int = 0,
    min_read_length: int = 0,
    include_deletions: bool = True,
    regions: list[str] | None = None,
) -> tuple[BamHeader, dict[str, np.ndarray]]:
    """Per-contig depth via difference arrays (samtools depth -a -J semantics).

    Returns (header, {contig: int32 depth vector}). ``regions`` restricts to
    named contigs (region strings "chr1" or "chr1:1000-2000"). CRAM inputs
    dispatch to the native CRAM 3.0 decoder (io/cram).
    """
    if str(path).endswith(".cram"):
        from variantcalling_tpu.io import cram

        return cram.depth_diff_arrays(
            path, min_bq=min_bq, min_mapq=min_mapq, min_read_length=min_read_length,
            include_deletions=include_deletions, regions=regions,
        )
    cov_ops = _COV_OPS_J if include_deletions else _COV_OPS
    region_contigs = None
    if regions:
        region_contigs = {r.split(":")[0] for r in regions}
    native_out = _depth_diff_arrays_native(
        path, min_bq, min_mapq, min_read_length, include_deletions, region_contigs
    )
    if native_out is not None:
        return native_out
    with BamReader(path) as bam:
        refs = bam.header.references
        diffs: dict[str, np.ndarray] = {}
        for name in refs:
            if region_contigs is None or name in region_contigs:
                diffs[name] = np.zeros(bam.header.lengths[name] + 1, dtype=np.int32)
        for aln in bam:
            if aln.flag & EXCLUDE_FLAGS or aln.ref_id < 0:
                continue
            if aln.mapq < min_mapq or aln.read_len < min_read_length:
                continue
            name = refs[aln.ref_id]
            if name not in diffs:
                continue
            diff = diffs[name]
            if min_bq > 0 and aln.qual is not None:
                _add_bq_filtered(diff, aln, min_bq, cov_ops)
                continue
            ref_pos = aln.pos
            for op, length in aln.cigar:
                if op in cov_ops:
                    diff[ref_pos] += 1
                    diff[min(ref_pos + length, len(diff) - 1)] -= 1
                if op in _REF_CONSUME:
                    ref_pos += length
        return bam.header, diffs


def _depth_diff_arrays_native(
    path: str,
    min_bq: int,
    min_mapq: int,
    min_read_length: int,
    include_deletions: bool,
    region_contigs: set[str] | None,
) -> tuple[BamHeader, dict[str, np.ndarray]] | None:
    """C++ fast path: whole-file BGZF inflate + native record walk."""
    from variantcalling_tpu import native

    if not native.available():
        return None
    with open(path, "rb") as fh:
        raw = fh.read()
    arr = native.bgzf_decompress_array(raw)
    del raw
    if arr is None:
        return None
    buf = memoryview(arr)  # zero-copy view for header parsing
    if bytes(buf[:4]) != b"BAM\x01":
        return None
    (l_text,) = struct.unpack_from("<i", buf, 4)
    off = 8 + l_text
    text = bytes(buf[8 : 8 + l_text]).rstrip(b"\x00").decode(errors="replace")
    (n_ref,) = struct.unpack_from("<i", buf, off)
    off += 4
    refs: list[str] = []
    lengths: dict[str, int] = {}
    for _ in range(n_ref):
        (l_name,) = struct.unpack_from("<i", buf, off)
        name = bytes(buf[off + 4 : off + 4 + l_name - 1]).decode()
        (l_ref,) = struct.unpack_from("<i", buf, off + 4 + l_name)
        off += 8 + l_name
        refs.append(name)
        lengths[name] = l_ref
    header = BamHeader(text, refs, lengths)
    starts = np.full(n_ref, -1, dtype=np.int64)
    lens = np.zeros(n_ref, dtype=np.int64)
    cursor = 0
    for i, name in enumerate(refs):
        lens[i] = lengths[name]
        if region_contigs is None or name in region_contigs:
            starts[i] = cursor
            cursor += lengths[name] + 1
    diff_flat = np.zeros(max(cursor, 1), dtype=np.int32)
    n = native.bam_depth(
        arr[off:],  # numpy slice: zero-copy view
        starts,
        lens,
        diff_flat,
        min_bq=min_bq,
        min_mapq=min_mapq,
        min_read_length=min_read_length,
        include_deletions=include_deletions,
        exclude_flags=EXCLUDE_FLAGS,
    )
    if n is None:
        return None
    diffs: dict[str, np.ndarray] = {}
    for i, name in enumerate(refs):
        if starts[i] >= 0:
            diffs[name] = diff_flat[starts[i] : starts[i] + lengths[name] + 1]
    return header, diffs


def _add_bq_filtered(diff: np.ndarray, aln: Alignment, min_bq: int, cov_ops: set) -> None:
    """Per-base quality filtering (-q): walk cigar over read and reference."""
    ref_pos = aln.pos
    read_pos = 0
    q = aln.qual
    for op, length in aln.cigar:
        consumes_read = op in (0, 1, 4, 7, 8)  # M, I, S, =, X
        if op in cov_ops:
            if op == 2:  # deletion: no base quals; counts with -J
                diff[ref_pos] += 1
                diff[min(ref_pos + length, len(diff) - 1)] -= 1
            else:
                ok = q[read_pos : read_pos + length] >= min_bq
                # run-length the pass mask into diff updates
                edges = np.flatnonzero(np.diff(np.concatenate([[0], ok.view(np.int8), [0]])))
                for s, e in zip(edges[::2], edges[1::2]):
                    diff[ref_pos + s] += 1
                    diff[min(ref_pos + e, len(diff) - 1)] -= 1
        if op in _REF_CONSUME:
            ref_pos += length
        if consumes_read:
            read_pos += length


def depth_vectors(header: BamHeader, diffs: dict[str, np.ndarray]) -> dict[str, np.ndarray]:
    """cumsum of the diff arrays -> per-base depth (length = contig length)."""
    return {name: np.cumsum(d[:-1], dtype=np.int32) for name, d in diffs.items()}
