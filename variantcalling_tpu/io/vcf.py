"""Columnar VCF/gVCF reader and writer (host-side ingest layer).

The reference reads VCFs through pysam/htslib one record at a time
(e.g. compress_gvcf.py:19, convert_haploid_regions.py:3) and through
``ugbio_core.vcfbed.vcftools.get_vcf_df`` into pandas. This framework's
ingest instead produces a **columnar** :class:`VariantTable` — numpy arrays
over all records — which featurization turns into padded device tensors.
The original tab-separated fields are retained so writers can rewrite only
the columns a pipeline touched (FILTER/INFO/FORMAT), keeping untouched
bytes identical to the input.

BGZF-compressed inputs (``.gz``) are readable via Python's gzip (BGZF is a
gzip-compatible framing); a C++ BGZF codec accelerates this path when built
(variantcalling_tpu/native).
"""

from __future__ import annotations

import gzip
import io as _io
import os
from dataclasses import dataclass, field

import numpy as np

MISSING = "."


# above this compressed size, keep constant-memory streaming via gzip
# rather than whole-file native inflation (shared by io/bed.py)
NATIVE_INFLATE_MAX_BYTES = 512 << 20


def _open_text(path: str):
    if str(path).endswith(".gz") or str(path).endswith(".bgz"):
        from variantcalling_tpu import native

        if native.available() and os.path.getsize(path) <= NATIVE_INFLATE_MAX_BYTES:
            with open(path, "rb") as fh:
                raw = fh.read()
            data = native.bgzf_decompress(raw)
            if data is not None:
                return _io.TextIOWrapper(_io.BytesIO(data), encoding="utf-8")
        return _io.TextIOWrapper(gzip.open(path, "rb"), encoding="utf-8")
    return open(path, "rt", encoding="utf-8")


@dataclass
class VcfHeader:
    """Parsed VCF header: meta lines (verbatim), contigs, field definitions, samples."""

    lines: list[str] = field(default_factory=list)  # '##...' lines, no newline
    samples: list[str] = field(default_factory=list)
    contigs: list[str] = field(default_factory=list)
    contig_lengths: dict[str, int] = field(default_factory=dict)
    infos: dict[str, dict] = field(default_factory=dict)  # id -> {Number, Type, Description}
    formats: dict[str, dict] = field(default_factory=dict)
    filters: dict[str, str] = field(default_factory=dict)

    @staticmethod
    def _parse_structured(line: str) -> dict:
        # ##INFO=<ID=DP,Number=1,Type=Integer,Description="...">
        inner = line[line.index("<") + 1 : line.rindex(">")]
        out: dict[str, str] = {}
        key = ""
        val = ""
        in_quotes = False
        target = "key"
        for ch in inner:
            if target == "key":
                if ch == "=":
                    target = "val"
                else:
                    key += ch
            else:
                if ch == '"':
                    in_quotes = not in_quotes
                    val += ch
                elif ch == "," and not in_quotes:
                    out[key] = val.strip('"')
                    key, val, target = "", "", "key"
                else:
                    val += ch
        if key:
            out[key] = val.strip('"')
        return out

    def add_meta_line(self, line: str) -> None:
        line = line.rstrip("\n")
        self.lines.append(line)
        if line.startswith("##contig="):
            d = self._parse_structured(line)
            name = d.get("ID", "")
            self.contigs.append(name)
            if "length" in d:
                try:
                    self.contig_lengths[name] = int(d["length"])
                except ValueError:
                    pass
        elif line.startswith("##INFO="):
            d = self._parse_structured(line)
            self.infos[d.get("ID", "")] = d
        elif line.startswith("##FORMAT="):
            d = self._parse_structured(line)
            self.formats[d.get("ID", "")] = d
        elif line.startswith("##FILTER="):
            d = self._parse_structured(line)
            self.filters[d.get("ID", "")] = d.get("Description", "")

    def ensure_info(self, info_id: str, number: str, info_type: str, description: str) -> None:
        if info_id not in self.infos:
            line = f'##INFO=<ID={info_id},Number={number},Type={info_type},Description="{description}">'
            self.add_meta_line(line)

    def ensure_format(self, fmt_id: str, number: str, fmt_type: str, description: str) -> None:
        if fmt_id not in self.formats:
            line = f'##FORMAT=<ID={fmt_id},Number={number},Type={fmt_type},Description="{description}">'
            self.add_meta_line(line)

    def ensure_filter(self, filter_id: str, description: str) -> None:
        if filter_id not in self.filters:
            self.add_meta_line(f'##FILTER=<ID={filter_id},Description="{description}">')

    def column_header(self) -> str:
        cols = ["#CHROM", "POS", "ID", "REF", "ALT", "QUAL", "FILTER", "INFO"]
        if self.samples:
            cols += ["FORMAT", *self.samples]
        return "\t".join(cols)


@dataclass
class VariantTable:
    """Columnar view of a VCF: one numpy array per column over all records.

    String-ish columns are object arrays; ragged per-record structures
    (ALTs, per-sample fields) stay host-side until featurization pads them
    into device tensors.
    """

    header: VcfHeader
    chrom: np.ndarray  # object (str)
    pos: np.ndarray  # int64, 1-based
    vid: np.ndarray  # object
    ref: np.ndarray  # object
    alt: np.ndarray  # object: comma-joined ALT string as in file ('.' possible)
    qual: np.ndarray  # float64 (nan for '.')
    filters: np.ndarray  # object: raw FILTER column string
    info: np.ndarray  # object: raw INFO column string
    fmt_keys: np.ndarray | None = None  # object: FORMAT column per record
    sample_cols: np.ndarray | None = None  # object (n, n_samples): raw sample strings

    def __len__(self) -> int:
        return len(self.pos)

    @property
    def n_samples(self) -> int:
        return len(self.header.samples)

    # -- derived columnar views ------------------------------------------------

    def alt_lists(self) -> list[list[str]]:
        return [[] if a in (MISSING, "") else a.split(",") for a in self.alt]

    def n_alts(self) -> np.ndarray:
        return np.fromiter(
            (0 if a in (MISSING, "") else a.count(",") + 1 for a in self.alt),
            dtype=np.int32,
            count=len(self),
        )

    def filter_sets(self) -> list[set[str]]:
        return [set() if f in (MISSING, "", "PASS") else set(f.split(";")) for f in self.filters]

    def info_field(self, name: str, dtype=np.float64, missing=np.nan, index: int = 0) -> np.ndarray:
        """Vectorized extraction of one INFO key (scalar or ``index``-th element)."""
        out = np.full(len(self), missing, dtype=dtype)
        key_eq = name + "="
        for i, s in enumerate(self.info):
            if s is None or s == MISSING:
                continue
            for part in s.split(";"):
                if part.startswith(key_eq):
                    v = part[len(key_eq) :]
                    if "," in v:
                        v = v.split(",")[index]
                    if v != MISSING and v != "":
                        try:
                            out[i] = dtype(v) if not isinstance(dtype, type) else np.dtype(dtype).type(v)
                        except (ValueError, TypeError):
                            pass
                    break
        return out

    def info_flag(self, name: str) -> np.ndarray:
        out = np.zeros(len(self), dtype=bool)
        for i, s in enumerate(self.info):
            if s is None or s == MISSING:
                continue
            for part in s.split(";"):
                if part == name or part.startswith(name + "="):
                    out[i] = True
                    break
        return out

    def format_field(self, name: str, sample: int = 0) -> list[str | None]:
        """Raw string of one FORMAT key for one sample, per record (None if absent)."""
        if self.fmt_keys is None or self.sample_cols is None:
            return [None] * len(self)
        out: list[str | None] = []
        for i in range(len(self)):
            keys = self.fmt_keys[i]
            if not keys or keys == MISSING:
                out.append(None)
                continue
            try:
                idx = keys.split(":").index(name)
            except ValueError:
                out.append(None)
                continue
            vals = self.sample_cols[i][sample].split(":")
            out.append(vals[idx] if idx < len(vals) else None)
        return out

    def genotypes(self, sample: int = 0) -> np.ndarray:
        """(n, 2) int8 diploid genotype; -1 for missing/haploid-second slot; phasing dropped."""
        gt_strs = self.format_field("GT", sample)
        out = np.full((len(self), 2), -1, dtype=np.int8)
        for i, g in enumerate(gt_strs):
            if not g:
                continue
            parts = g.replace("|", "/").split("/")
            for j, p in enumerate(parts[:2]):
                if p not in (MISSING, ""):
                    out[i, j] = int(p)
        return out

    def format_numeric(self, name: str, sample: int = 0, max_len: int | None = None, missing=-1) -> np.ndarray:
        """Padded (n, max_len) numeric tensor of a comma-listed FORMAT field (e.g. PL, AD)."""
        raw = self.format_field(name, sample)
        split = [r.split(",") if r not in (None, MISSING, "") else [] for r in raw]
        if max_len is None:
            max_len = max((len(s) for s in split), default=0)
        out = np.full((len(self), max_len), missing, dtype=np.float64)
        for i, vals in enumerate(split):
            for j, v in enumerate(vals[:max_len]):
                if v not in (MISSING, ""):
                    try:
                        out[i, j] = float(v)
                    except ValueError:
                        pass
        return out


def read_vcf(
    path: str,
    region: tuple[str, int, int] | None = None,
    drop_format: bool = False,
) -> VariantTable:
    """Parse a VCF/gVCF (.vcf or .vcf.gz) into a :class:`VariantTable`.

    ``region`` is (chrom, start_1based, end_inclusive); served from the
    sibling ``.tbi`` index when present (io/tabix — only covering BGZF
    blocks are inflated), streaming filter otherwise.
    """
    header = VcfHeader()
    chrom: list[str] = []
    pos: list[int] = []
    vid: list[str] = []
    ref: list[str] = []
    alt: list[str] = []
    qual: list[float] = []
    filt: list[str] = []
    info: list[str] = []
    fmt_keys: list[str] = []
    sample_cols: list[tuple[str, ...]] = []
    n_samples = 0

    indexed_lines = None
    if region is not None and str(path).endswith(".gz") and os.path.exists(str(path) + ".tbi"):
        from variantcalling_tpu.io.tabix import read_region_lines

        indexed_lines = read_region_lines(str(path), region[0], region[1] - 1, region[2])

    def _indexed_source(fh):
        # header from the file head, records straight from covering blocks
        for line in fh:
            if not line.startswith("#"):
                break
            yield line
        for line in indexed_lines:
            yield line + "\n"

    if indexed_lines is not None:
        # stream just the header (stops at the first record); the records
        # themselves come from the index's covering blocks only
        opener = _io.TextIOWrapper(gzip.open(path, "rb"), encoding="utf-8")
    else:
        opener = _open_text(path)
    with opener as fh:
        source = _indexed_source(fh) if indexed_lines is not None else fh
        for line in source:
            if line.startswith("##"):
                header.add_meta_line(line)
                continue
            if line.startswith("#"):
                cols = line.rstrip("\n").split("\t")
                if len(cols) > 9:
                    header.samples = cols[9:]
                n_samples = len(header.samples)
                continue
            parts = line.rstrip("\n").split("\t")
            if region is not None:
                if parts[0] != region[0]:
                    continue
                p = int(parts[1])
                if p < region[1] or p > region[2]:
                    continue
            chrom.append(parts[0])
            pos.append(int(parts[1]))
            vid.append(parts[2])
            ref.append(parts[3])
            alt.append(parts[4])
            qual.append(float(parts[5]) if parts[5] != MISSING else np.nan)
            filt.append(parts[6])
            info.append(parts[7] if len(parts) > 7 else MISSING)
            if n_samples and not drop_format:
                fmt_keys.append(parts[8] if len(parts) > 8 else MISSING)
                sample_cols.append(tuple(parts[9 : 9 + n_samples]))

    def obj(x):
        a = np.empty(len(x), dtype=object)
        a[:] = x
        return a

    table = VariantTable(
        header=header,
        chrom=obj(chrom),
        pos=np.asarray(pos, dtype=np.int64),
        vid=obj(vid),
        ref=obj(ref),
        alt=obj(alt),
        qual=np.asarray(qual, dtype=np.float64),
        filters=obj(filt),
        info=obj(info),
    )
    if n_samples and not drop_format:
        table.fmt_keys = obj(fmt_keys)
        sc = np.empty((len(sample_cols), n_samples), dtype=object)
        for i, tup in enumerate(sample_cols):
            sc[i, :] = tup
        table.sample_cols = sc
    return table


def format_qual(q: float) -> str:
    if q is None or (isinstance(q, float) and np.isnan(q)):
        return MISSING
    if float(q) == int(q):
        return str(int(q))
    return f"{q:g}"


def write_vcf(
    path: str,
    table: VariantTable,
    new_filters: np.ndarray | None = None,
    extra_info: dict[str, np.ndarray] | None = None,
    sample_overrides: dict[int, np.ndarray] | None = None,
    fmt_override: np.ndarray | None = None,
    index: bool = True,
) -> None:
    """Write a VariantTable back to VCF, rewriting only the requested columns.

    - ``new_filters``: object array replacing the FILTER column.
    - ``extra_info``: info-key -> per-record value (np.nan/None skips a record;
      ``True`` writes a bare flag). Appended to the existing INFO string.
    - ``sample_overrides``: sample index -> object array of replacement
      sample strings; ``fmt_override`` replaces the FORMAT column.
    - ``index``: for ``.gz`` outputs, also build the sibling ``.tbi``
      (io/tabix) so htslib tools can consume the file directly.
    """
    if str(path).endswith(".gz"):
        from variantcalling_tpu.io.bgzf import BgzfWriter

        opener = lambda p, _mode: BgzfWriter(p)  # noqa: E731 — tabix-compatible blocks
    else:
        opener = open
    with opener(path, "wt") as out:
        for line in table.header.lines:
            out.write(line + "\n")
        out.write(table.header.column_header() + "\n")
        n = len(table)
        for i in range(n):
            info_s = table.info[i]
            if extra_info:
                parts = [] if info_s in (MISSING, "", None) else [info_s]
                for k, vals in extra_info.items():
                    v = vals[i]
                    if v is None or (isinstance(v, float) and np.isnan(v)):
                        continue
                    if v is True:
                        parts.append(k)
                    elif isinstance(v, (float, np.floating)):
                        parts.append(f"{k}={float(v):g}")
                    else:
                        parts.append(f"{k}={v}")
                info_s = ";".join(parts) if parts else MISSING
            filt_s = new_filters[i] if new_filters is not None else table.filters[i]
            cols = [
                table.chrom[i],
                str(table.pos[i]),
                table.vid[i],
                table.ref[i],
                table.alt[i],
                format_qual(table.qual[i]),
                filt_s,
                info_s,
            ]
            if table.fmt_keys is not None:
                cols.append(fmt_override[i] if fmt_override is not None else table.fmt_keys[i])
                for s in range(table.n_samples):
                    if sample_overrides and s in sample_overrides:
                        cols.append(sample_overrides[s][i])
                    else:
                        cols.append(table.sample_cols[i][s])
            out.write("\t".join(cols) + "\n")
    if index and str(path).endswith(".gz"):
        from variantcalling_tpu.io.tabix import build_tabix_index

        try:
            build_tabix_index(str(path))
        except (ValueError, OSError):
            pass  # unsorted/odd inputs: the VCF itself is still valid
