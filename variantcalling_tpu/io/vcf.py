"""Columnar VCF/gVCF reader and writer (host-side ingest layer).

The reference reads VCFs through pysam/htslib one record at a time
(e.g. compress_gvcf.py:19, convert_haploid_regions.py:3) and through
``ugbio_core.vcfbed.vcftools.get_vcf_df`` into pandas. This framework's
ingest instead produces a **columnar** :class:`VariantTable` — numpy arrays
over all records — which featurization turns into padded device tensors.
The original tab-separated fields are retained so writers can rewrite only
the columns a pipeline touched (FILTER/INFO/FORMAT), keeping untouched
bytes identical to the input.

BGZF-compressed inputs (``.gz``) are readable via Python's gzip (BGZF is a
gzip-compatible framing); a C++ BGZF codec accelerates this path when built
(variantcalling_tpu/native).
"""

from __future__ import annotations

import gzip
import io as _io
import os
import threading
import time
from dataclasses import dataclass, field

import numpy as np

from variantcalling_tpu import knobs

MISSING = "."


# above this compressed size, keep constant-memory streaming via gzip
# rather than whole-file native inflation (shared by io/bed.py)
NATIVE_INFLATE_MAX_BYTES = 512 << 20


def _open_text(path: str):
    if str(path).endswith(".gz") or str(path).endswith(".bgz"):
        from variantcalling_tpu import native

        if native.available() and os.path.getsize(path) <= NATIVE_INFLATE_MAX_BYTES:
            with open(path, "rb") as fh:
                raw = fh.read()
            data = native.bgzf_decompress(raw)
            if data is not None:
                return _io.TextIOWrapper(_io.BytesIO(data), encoding="utf-8")
        return _io.TextIOWrapper(gzip.open(path, "rb"), encoding="utf-8")
    return open(path, "rt", encoding="utf-8")


@dataclass
class VcfHeader:
    """Parsed VCF header: meta lines (verbatim), contigs, field definitions, samples."""

    lines: list[str] = field(default_factory=list)  # '##...' lines, no newline
    samples: list[str] = field(default_factory=list)
    contigs: list[str] = field(default_factory=list)
    contig_lengths: dict[str, int] = field(default_factory=dict)
    infos: dict[str, dict] = field(default_factory=dict)  # id -> {Number, Type, Description}
    formats: dict[str, dict] = field(default_factory=dict)
    filters: dict[str, str] = field(default_factory=dict)

    @staticmethod
    def _parse_structured(line: str) -> dict:
        # ##INFO=<ID=DP,Number=1,Type=Integer,Description="...">
        inner = line[line.index("<") + 1 : line.rindex(">")]
        out: dict[str, str] = {}
        key = ""
        val = ""
        in_quotes = False
        target = "key"
        for ch in inner:
            if target == "key":
                if ch == "=":
                    target = "val"
                else:
                    key += ch
            else:
                if ch == '"':
                    in_quotes = not in_quotes
                    val += ch
                elif ch == "," and not in_quotes:
                    out[key] = val.strip('"')
                    key, val, target = "", "", "key"
                else:
                    val += ch
        if key:
            out[key] = val.strip('"')
        return out

    def add_meta_line(self, line: str) -> None:
        line = line.rstrip("\n")
        self.lines.append(line)
        if line.startswith("##contig="):
            d = self._parse_structured(line)
            name = d.get("ID", "")
            self.contigs.append(name)
            if "length" in d:
                try:
                    self.contig_lengths[name] = int(d["length"])
                except ValueError:
                    pass
        elif line.startswith("##INFO="):
            d = self._parse_structured(line)
            self.infos[d.get("ID", "")] = d
        elif line.startswith("##FORMAT="):
            d = self._parse_structured(line)
            self.formats[d.get("ID", "")] = d
        elif line.startswith("##FILTER="):
            d = self._parse_structured(line)
            self.filters[d.get("ID", "")] = d.get("Description", "")

    def ensure_info(self, info_id: str, number: str, info_type: str, description: str) -> None:
        if info_id not in self.infos:
            line = f'##INFO=<ID={info_id},Number={number},Type={info_type},Description="{description}">'
            self.add_meta_line(line)

    def ensure_format(self, fmt_id: str, number: str, fmt_type: str, description: str) -> None:
        if fmt_id not in self.formats:
            line = f'##FORMAT=<ID={fmt_id},Number={number},Type={fmt_type},Description="{description}">'
            self.add_meta_line(line)

    def ensure_filter(self, filter_id: str, description: str) -> None:
        if filter_id not in self.filters:
            self.add_meta_line(f'##FILTER=<ID={filter_id},Description="{description}">')

    def column_header(self) -> str:
        cols = ["#CHROM", "POS", "ID", "REF", "ALT", "QUAL", "FILTER", "INFO"]
        if self.samples:
            cols += ["FORMAT", *self.samples]
        return "\t".join(cols)


@dataclass
class NativeAux:
    """Products of the native one-pass VCF scan (native/src vctpu_vcf_parse).

    Row-aligned with the owning :class:`VariantTable`; ``buf`` is the shared
    uncompressed text buffer, spans are [start, end) byte offsets into it.
    Serves three purposes: (1) numeric caches (FORMAT GT/GQ/DP/AD, hot INFO
    keys, allele classification) so featurization never re-parses strings,
    (2) lazy FORMAT/sample materialization, (3) byte-slice VCF writeback.
    """

    buf: np.ndarray  # uint8 text
    line_spans: np.ndarray  # (n, 2)
    tail_spans: np.ndarray  # (n, 2): FORMAT..line-end (empty span if no samples)
    info_spans: np.ndarray  # (n, 2)
    filter_spans: np.ndarray  # (n, 2)
    gt: np.ndarray  # (n, 2) int8
    gt_phased: np.ndarray  # (n,) uint8
    gq: np.ndarray  # (n,) float32, NaN missing
    dp_fmt: np.ndarray  # (n,) float32
    ad: np.ndarray  # (n, 3) float32: ref, alt1, positive-total
    info_vals: np.ndarray  # (n, len(info_keys)) float64
    info_keys: tuple
    alle: dict  # aclass/indel_length/indel_nuc/ref_code/alt_code/n_alts/ref_len
    has_format: bool = True  # False after drop_format: no sample data, no buffer

    def take(self, keep: np.ndarray) -> "NativeAux":
        return NativeAux(
            buf=self.buf,
            has_format=self.has_format,
            line_spans=self.line_spans[keep],
            tail_spans=self.tail_spans[keep],
            info_spans=self.info_spans[keep],
            filter_spans=self.filter_spans[keep],
            gt=self.gt[keep],
            gt_phased=self.gt_phased[keep],
            gq=self.gq[keep],
            dp_fmt=self.dp_fmt[keep],
            ad=self.ad[keep],
            info_vals=self.info_vals[keep],
            info_keys=self.info_keys,
            alle={k: v[keep] for k, v in self.alle.items()},
        )


class FactorizedColumn:
    """Low-cardinality string column held as (codes, uniques).

    The filter pipeline's FILTER column has <=6 distinct values over 5M
    records; carrying integer codes end to end skips the ~1.3s
    pd.factorize of an object array on the writeback hot path. Quacks
    enough like an object array (len/iter/getitem/== str/np.asarray) that
    report code and tests can treat it as one.
    """

    __slots__ = ("codes", "uniques")

    def __init__(self, codes: np.ndarray, uniques: list[str]):
        self.codes = np.ascontiguousarray(codes, dtype=np.int32)
        self.uniques = list(uniques)

    def __len__(self) -> int:
        return len(self.codes)

    def __iter__(self):
        u = self.uniques
        return (u[c] for c in self.codes)

    def __getitem__(self, i):
        if isinstance(i, (int, np.integer)):
            return self.uniques[self.codes[i]]
        return FactorizedColumn(self.codes[i], self.uniques)

    def __eq__(self, other):  # vectorized `filters == "PASS"`
        if isinstance(other, str):
            try:
                return self.codes == self.uniques.index(other)
            except ValueError:
                return np.zeros(len(self.codes), dtype=bool)
        return NotImplemented

    def __ne__(self, other):
        eq = self.__eq__(other)
        return NotImplemented if eq is NotImplemented else ~eq

    def __array__(self, dtype=None, copy=None):
        return np.asarray(self.uniques, dtype=object)[self.codes]

    def to_object(self) -> np.ndarray:
        return self.__array__()


class _LazyCols:
    """Deferred string columns: (name -> (n,2) span array) into a shared buffer.

    Materialization decodes buffer slices once per column on first access;
    row subsets just subset the span arrays, so a pipeline that never
    touches REF/ALT/INFO strings never pays for them. The backing is the
    SAME bytes object the NativeAux buffer views (np.frombuffer) — or a
    uint8 array/memmap on the chunked-ingest path — so laziness adds no
    memory beyond the span arrays.
    """

    __slots__ = ("buf", "spans")

    def __init__(self, buf, spans: dict):
        self.buf = buf
        self.spans = spans

    def take(self, keep) -> "_LazyCols":
        return _LazyCols(self.buf, {k: v[keep] for k, v in self.spans.items()})

    def materialize(self, name: str) -> np.ndarray:
        spans = self.spans[name].tolist()
        buf = self.buf
        if isinstance(buf, np.ndarray):
            # one decode-side copy per chunk, cached so sibling columns
            # (vid/ref/alt/filters/info) don't re-copy the same buffer
            self.buf = buf = bytes(memoryview(buf))
        out = np.empty(len(spans), dtype=object)
        for i, (a, b) in enumerate(spans):
            out[i] = buf[a:b].decode("latin-1")
        return out


class VariantTable:
    """Columnar view of a VCF: one numpy array per column over all records.

    String-ish columns are object arrays; ragged per-record structures
    (ALTs, per-sample fields) stay host-side until featurization pads them
    into device tensors. ``aux`` (native ingest only) carries pre-parsed
    numeric caches + raw byte spans; use :meth:`subset` for row filtering so
    it stays aligned. ``fmt_keys``/``sample_cols`` are lazy on the native
    path: reading them materializes the strings from the raw buffer.
    """

    def __init__(
        self,
        header: VcfHeader,
        chrom: np.ndarray,
        pos: np.ndarray,
        vid: np.ndarray,
        ref: np.ndarray,
        alt: np.ndarray,
        qual: np.ndarray,
        filters: np.ndarray,
        info: np.ndarray,
        fmt_keys: np.ndarray | None = None,
        sample_cols: np.ndarray | None = None,
        aux: NativeAux | None = None,
        lazy_cols: "_LazyCols | None" = None,
        chrom_codes: np.ndarray | None = None,
        chrom_names: np.ndarray | None = None,
    ):
        self.header = header
        self.chrom = chrom
        #: native-ingest bonus: the scan's integer CHROM dictionary codes
        #: (+ name table), so per-chunk contig grouping (featurize
        #: _contig_runs) never re-factorizes 1M Python strings on the
        #: scoring hot path
        self.chrom_codes = chrom_codes
        self.chrom_names = chrom_names
        self.pos = pos
        self._vid = vid
        self._ref = ref
        self._alt = alt
        self.qual = qual
        self._filters = filters
        self._info = info
        self._fmt_keys = fmt_keys
        self._sample_cols = sample_cols
        self.aux = aux
        self._lazy = lazy_cols

    def __len__(self) -> int:
        return len(self.pos)

    def _col(self, slot: str):
        v = getattr(self, "_" + slot)
        if v is None and self._lazy is not None:
            v = self._lazy.materialize(slot)
            setattr(self, "_" + slot, v)
        return v

    # The five record string columns are lazy on the native-ingest path:
    # spans into the shared text buffer until first touched (the filter
    # pipeline never touches REF/ALT/INFO strings — allele classes come from
    # the native numeric cache and writeback splices byte spans).
    vid = property(lambda s: s._col("vid"), lambda s, v: setattr(s, "_vid", v))
    ref = property(lambda s: s._col("ref"), lambda s, v: setattr(s, "_ref", v))
    alt = property(lambda s: s._col("alt"), lambda s, v: setattr(s, "_alt", v))
    filters = property(lambda s: s._col("filters"), lambda s, v: setattr(s, "_filters", v))
    info = property(lambda s: s._col("info"), lambda s, v: setattr(s, "_info", v))

    @property
    def n_samples(self) -> int:
        return len(self.header.samples)

    @property
    def fmt_keys(self) -> np.ndarray | None:
        if self._fmt_keys is None and self.aux is not None and self.n_samples > 0:
            self.materialize_format()
        return self._fmt_keys

    @fmt_keys.setter
    def fmt_keys(self, v) -> None:
        self._fmt_keys = v

    @property
    def sample_cols(self) -> np.ndarray | None:
        if self._sample_cols is None and self.aux is not None and self.n_samples > 0:
            self.materialize_format()
        return self._sample_cols

    @sample_cols.setter
    def sample_cols(self, v) -> None:
        self._sample_cols = v

    @property
    def format_materialized(self) -> bool:
        """True when FORMAT/sample strings exist in memory (possibly edited)."""
        return self._fmt_keys is not None

    def subset(self, keep: np.ndarray) -> "VariantTable":
        """Row-subset every column (and aux) by a boolean/index array."""
        lazy_pending = self._lazy is not None and any(
            getattr(self, "_" + f) is None for f in ("vid", "ref", "alt", "filters", "info")
        )
        return VariantTable(
            header=self.header,
            chrom=self.chrom[keep],
            chrom_codes=self.chrom_codes[keep] if self.chrom_codes is not None else None,
            chrom_names=self.chrom_names,
            pos=self.pos[keep],
            vid=self._vid[keep] if self._vid is not None else None,
            ref=self._ref[keep] if self._ref is not None else None,
            alt=self._alt[keep] if self._alt is not None else None,
            qual=self.qual[keep],
            filters=self._filters[keep] if self._filters is not None else None,
            info=self._info[keep] if self._info is not None else None,
            lazy_cols=self._lazy.take(keep) if lazy_pending else None,
            fmt_keys=self._fmt_keys[keep] if self._fmt_keys is not None else None,
            sample_cols=self._sample_cols[keep] if self._sample_cols is not None else None,
            aux=self.aux.take(keep) if self.aux is not None else None,
        )

    def materialize_format(self) -> None:
        """Fill fmt_keys/sample_cols from the native tail spans (lazy path)."""
        if self._fmt_keys is not None or self.aux is None or self.n_samples == 0:
            return
        if not self.aux.has_format or self.aux.buf is None:
            return  # drop_format ingest: no sample data, mirroring the Python path
        text = self.aux.buf.tobytes().decode("latin-1")
        spans = self.aux.tail_spans.tolist()
        n = len(self)
        k = self.n_samples
        fmt = np.empty(n, dtype=object)
        sc = np.empty((n, k), dtype=object)
        for i, (a, b) in enumerate(spans):
            parts = text[a:b].split("\t") if b > a else [MISSING]
            fmt[i] = parts[0]
            for s in range(k):
                sc[i, s] = parts[1 + s] if 1 + s < len(parts) else MISSING
        self._fmt_keys = fmt
        self._sample_cols = sc

    # -- derived columnar views ------------------------------------------------

    def alt_lists(self) -> list[list[str]]:
        return [[] if a in (MISSING, "") else a.split(",") for a in self.alt]

    def n_alts(self) -> np.ndarray:
        if self.aux is not None:
            return self.aux.alle["n_alts"].copy()
        return np.fromiter(
            (0 if a in (MISSING, "") else a.count(",") + 1 for a in self.alt),
            dtype=np.int32,
            count=len(self),
        )

    def filter_sets(self) -> list[set[str]]:
        return [set() if f in (MISSING, "", "PASS") else set(f.split(";")) for f in self.filters]

    def info_field(self, name: str, dtype=np.float64, missing=np.nan, index: int = 0) -> np.ndarray:
        """Vectorized extraction of one INFO key (scalar or ``index``-th element)."""
        if self.aux is not None and index == 0 and name in self.aux.info_keys:
            vals = self.aux.info_vals[:, self.aux.info_keys.index(name)]
            if np.issubdtype(np.dtype(dtype) if not isinstance(dtype, type) else dtype, np.floating) or dtype is float:
                out = vals.astype(dtype)
                if not (isinstance(missing, float) and np.isnan(missing)):
                    out = np.where(np.isnan(vals), missing, out)
                return out
            out = np.full(len(self), missing, dtype=dtype)
            ok = ~np.isnan(vals)
            out[ok] = vals[ok].astype(dtype)
            return out
        out = np.full(len(self), missing, dtype=dtype)
        key_eq = name + "="
        for i, s in enumerate(self.info):
            if s is None or s == MISSING:
                continue
            for part in s.split(";"):
                if part.startswith(key_eq):
                    v = part[len(key_eq) :]
                    if "," in v:
                        v = v.split(",")[index]
                    if v != MISSING and v != "":
                        try:
                            out[i] = dtype(v) if not isinstance(dtype, type) else np.dtype(dtype).type(v)
                        except (ValueError, TypeError):
                            pass
                    break
        return out

    def info_flag(self, name: str) -> np.ndarray:
        out = np.zeros(len(self), dtype=bool)
        for i, s in enumerate(self.info):
            if s is None or s == MISSING:
                continue
            for part in s.split(";"):
                if part == name or part.startswith(name + "="):
                    out[i] = True
                    break
        return out

    def format_field(self, name: str, sample: int = 0) -> list[str | None]:
        """Raw string of one FORMAT key for one sample, per record (None if absent)."""
        if self.fmt_keys is None or self.sample_cols is None:  # property materializes lazily
            return [None] * len(self)
        out: list[str | None] = []
        for i in range(len(self)):
            keys = self.fmt_keys[i]
            if not keys or keys == MISSING:
                out.append(None)
                continue
            try:
                idx = keys.split(":").index(name)
            except ValueError:
                out.append(None)
                continue
            vals = self.sample_cols[i][sample].split(":")
            out.append(vals[idx] if idx < len(vals) else None)
        return out

    def genotypes(self, sample: int = 0) -> np.ndarray:
        """(n, 2) int8 diploid genotype; -1 for missing/haploid-second slot; phasing dropped."""
        if sample == 0 and self.aux is not None:
            return self.aux.gt.copy()  # cache stays pristine if callers edit
        gt_strs = self.format_field("GT", sample)
        out = np.full((len(self), 2), -1, dtype=np.int8)
        for i, g in enumerate(gt_strs):
            if not g:
                continue
            parts = g.replace("|", "/").split("/")
            for j, p in enumerate(parts[:2]):
                if p not in (MISSING, ""):
                    out[i, j] = int(p)
        return out

    def format_numeric(self, name: str, sample: int = 0, max_len: int | None = None, missing=-1) -> np.ndarray:
        """Padded (n, max_len) numeric tensor of a comma-listed FORMAT field (e.g. PL, AD)."""
        if sample == 0 and self.aux is not None and name in ("GQ", "DP") and max_len in (None, 1):
            vals = self.aux.gq if name == "GQ" else self.aux.dp_fmt
            out = vals.astype(np.float64)[:, None]
            if not (isinstance(missing, float) and np.isnan(missing)):
                out = np.where(np.isnan(out), missing, out)
            return out
        raw = self.format_field(name, sample)
        split = [r.split(",") if r not in (None, MISSING, "") else [] for r in raw]
        if max_len is None:
            max_len = max((len(s) for s in split), default=0)
        out = np.full((len(self), max_len), missing, dtype=np.float64)
        for i, vals in enumerate(split):
            for j, v in enumerate(vals[:max_len]):
                if v not in (MISSING, ""):
                    try:
                        out[i, j] = float(v)
                    except ValueError:
                        pass
        return out


def parse_header_bytes(bufb: bytes) -> tuple[VcfHeader, int]:
    """Parse the '#' header region of a VCF byte buffer.

    Returns (header, offset of the first record line). Shared by the
    whole-file native ingest and the chunked streaming reader so the two
    can never disagree on header handling.
    """
    header = VcfHeader()
    off, n = 0, len(bufb)
    while off < n:
        nl = bufb.find(b"\n", off)
        end = nl if nl >= 0 else n
        if end > off and bufb[off : off + 1] != b"#":
            break
        line = bufb[off:end].decode("utf-8", "replace")
        if line.startswith("##"):
            header.add_meta_line(line)
        elif line.startswith("#"):
            cols = line.rstrip("\r").split("\t")
            if len(cols) > 9:
                header.samples = cols[9:]
        off = end + 1
    return header, min(off, n)


def _read_vcf_native(path: str, drop_format: bool = False) -> VariantTable | None:
    """Whole-file ingest through the C++ one-pass scanner (native/src).

    Numeric columns, sample-0 FORMAT numerics, hot INFO keys and allele
    classes come out of the scan as flat arrays; only the short string
    columns are materialized here. FORMAT/sample strings stay lazy
    (NativeAux spans). Returns None when the native library is unavailable
    (caller uses the streaming Python parser).
    """
    from variantcalling_tpu import native

    if not native.available():
        return None
    if str(path).endswith((".gz", ".bgz")):
        if os.path.getsize(path) > NATIVE_INFLATE_MAX_BYTES:
            return None
        with open(path, "rb") as fh:
            raw = fh.read()
        arr = native.bgzf_decompress_array(raw)
        if arr is None:
            return None
        bufb = arr.tobytes()
    else:
        with open(path, "rb") as fh:
            bufb = fh.read()
    buf_np = np.frombuffer(bufb, dtype=np.uint8)

    header, _ = parse_header_bytes(bufb)

    parsed = native.vcf_parse(buf_np, len(header.samples))
    if parsed is None:
        return None
    return _table_from_parsed(parsed, header, bufb, buf_np, drop_format)


def _table_from_parsed(parsed: dict, header: VcfHeader, bufb, buf_np: np.ndarray,
                       drop_format: bool) -> VariantTable:
    """Assemble a VariantTable from a native scan result over ``buf_np``.

    ``bufb`` backs the lazy string columns (bytes for whole-file ingest, a
    uint8 view for chunked ingest). Shared by :func:`_read_vcf_native` and
    :class:`VcfChunkReader` so whole-file and chunked tables are built
    identically.
    """
    nrec = parsed["n"]

    # the five record string columns stay lazy (spans into the shared byte
    # buffer): the hot pipelines never touch them, so ingest skips ~70% of
    # its old wallclock and laziness costs no extra buffer copy
    lazy = _LazyCols(
        bufb,
        {
            "vid": parsed["id_spans"],
            "ref": parsed["ref_spans"],
            "alt": parsed["alt_spans"],
            "filters": parsed["filter_spans"],
            "info": parsed["info_spans"],
        },
    )

    from variantcalling_tpu import native

    chrom_names = np.array(parsed["chroms"] + [""], dtype=object)
    if drop_format:
        # mirror the Python path: no sample data retained, and release the
        # text buffer (numeric/INFO/allele caches are kept — they are small)
        aux = NativeAux(
            buf=None,
            has_format=False,
            line_spans=np.zeros((nrec, 2), dtype=np.int64),
            tail_spans=np.zeros((nrec, 2), dtype=np.int64),
            info_spans=np.zeros((nrec, 2), dtype=np.int64),
            filter_spans=np.zeros((nrec, 2), dtype=np.int64),
            gt=np.full((nrec, 2), -1, dtype=np.int8),
            gt_phased=np.zeros(nrec, dtype=np.uint8),
            gq=np.full(nrec, np.nan, dtype=np.float32),
            dp_fmt=np.full(nrec, np.nan, dtype=np.float32),
            ad=np.full((nrec, 3), np.nan, dtype=np.float32),
            info_vals=parsed["info_vals"],
            info_keys=tuple(native.VCF_INFO_KEYS),
            alle={
                k: parsed[k]
                for k in ("aclass", "indel_length", "indel_nuc", "ref_code", "alt_code", "n_alts", "ref_len")
            },
        )
    else:
        aux = NativeAux(
            buf=buf_np,
            line_spans=parsed["line_spans"],
            tail_spans=parsed["tail_spans"],
            info_spans=parsed["info_spans"],
            filter_spans=parsed["filter_spans"],
            gt=parsed["gt"],
            gt_phased=parsed["gt_phased"],
            gq=parsed["gq"],
            dp_fmt=parsed["dp_fmt"],
            ad=parsed["ad"],
            info_vals=parsed["info_vals"],
            info_keys=tuple(native.VCF_INFO_KEYS),
            alle={
                k: parsed[k]
                for k in ("aclass", "indel_length", "indel_nuc", "ref_code", "alt_code", "n_alts", "ref_len")
            },
        )
    if drop_format:
        # drop_format's contract is "release the buffer": materialize the
        # five string columns eagerly so nothing pins the uncompressed text
        eager = {k: lazy.materialize(k) for k in ("vid", "ref", "alt", "filters", "info")}
        lazy = None
    else:
        eager = dict.fromkeys(("vid", "ref", "alt", "filters", "info"))
    return VariantTable(
        header=header,
        chrom=chrom_names[parsed["chrom_codes"]] if nrec else np.empty(0, dtype=object),
        chrom_codes=np.ascontiguousarray(parsed["chrom_codes"]) if nrec else None,
        chrom_names=chrom_names,
        pos=parsed["pos"],
        vid=eager["vid"],
        ref=eager["ref"],
        alt=eager["alt"],
        qual=parsed["qual"],
        filters=eager["filters"],
        info=eager["info"],
        lazy_cols=lazy,
        aux=aux,
    )


def read_vcf(
    path: str,
    region: tuple[str, int, int] | None = None,
    drop_format: bool = False,
) -> VariantTable:
    """Parse a VCF/gVCF (.vcf or .vcf.gz) into a :class:`VariantTable`.

    Whole-file reads go through the native C++ scanner when built
    (:func:`_read_vcf_native`); ``region`` is (chrom, start_1based,
    end_inclusive), served from the sibling ``.tbi`` index when present
    (io/tabix — only covering BGZF blocks are inflated), streaming filter
    otherwise.
    """
    if region is None:
        table = _read_vcf_native(path, drop_format=drop_format)
        if table is not None:
            return table
    header = VcfHeader()
    chrom: list[str] = []
    pos: list[int] = []
    vid: list[str] = []
    ref: list[str] = []
    alt: list[str] = []
    qual: list[float] = []
    filt: list[str] = []
    info: list[str] = []
    fmt_keys: list[str] = []
    sample_cols: list[tuple[str, ...]] = []
    n_samples = 0

    indexed_lines = None
    if region is not None and str(path).endswith(".gz") and os.path.exists(str(path) + ".tbi"):
        from variantcalling_tpu.io.tabix import read_region_lines

        indexed_lines = read_region_lines(str(path), region[0], region[1] - 1, region[2])

    def _indexed_source(fh):
        # header from the file head, records straight from covering blocks
        for line in fh:
            if not line.startswith("#"):
                break
            yield line
        for line in indexed_lines:
            yield line + "\n"

    if indexed_lines is not None:
        # stream just the header (stops at the first record); the records
        # themselves come from the index's covering blocks only
        opener = _io.TextIOWrapper(gzip.open(path, "rb"), encoding="utf-8")
    else:
        opener = _open_text(path)
    with opener as fh:
        source = _indexed_source(fh) if indexed_lines is not None else fh
        for line in source:
            if line.startswith("##"):
                header.add_meta_line(line)
                continue
            if line.startswith("#"):
                cols = line.rstrip("\n").split("\t")
                if len(cols) > 9:
                    header.samples = cols[9:]
                n_samples = len(header.samples)
                continue
            parts = line.rstrip("\n").split("\t")
            if region is not None:
                if parts[0] != region[0]:
                    continue
                p = int(parts[1])
                if p < region[1] or p > region[2]:
                    continue
            chrom.append(parts[0])
            pos.append(int(parts[1]))
            vid.append(parts[2])
            ref.append(parts[3])
            alt.append(parts[4])
            qual.append(float(parts[5]) if parts[5] != MISSING else np.nan)
            filt.append(parts[6])
            info.append(parts[7] if len(parts) > 7 else MISSING)
            if n_samples and not drop_format:
                fmt_keys.append(parts[8] if len(parts) > 8 else MISSING)
                sample_cols.append(tuple(parts[9 : 9 + n_samples]))

    def obj(x):
        a = np.empty(len(x), dtype=object)
        a[:] = x
        return a

    table = VariantTable(
        header=header,
        chrom=obj(chrom),
        pos=np.asarray(pos, dtype=np.int64),
        vid=obj(vid),
        ref=obj(ref),
        alt=obj(alt),
        qual=np.asarray(qual, dtype=np.float64),
        filters=obj(filt),
        info=obj(info),
    )
    if n_samples and not drop_format:
        table.fmt_keys = obj(fmt_keys)
        sc = np.empty((len(sample_cols), n_samples), dtype=object)
        for i, tup in enumerate(sample_cols):
            sc[i, :] = tup
        table.sample_cols = sc
    return table


#: default streaming chunk size (bytes of VCF text per pipeline item);
#: ~8 MB is ~40-120K records of a typical callset. The parallel host-IO
#: layout re-tuned this down from 16 MB: chunks are now the fan-out
#: granularity of the worker pool, and finer chunks pack the ordered
#: window better (1M leg: 8 MB ≈ 1.19M v/s vs 16 MB ≈ 1.08M; 5M leg:
#: 1.15M vs 1.13M on the 2-core container) while a few in-flight chunks
#: still bound pipeline memory at O(100 MB)
STREAM_CHUNK_BYTES = 8 << 20


class _ParallelBgzfStream:
    """File-like ``read(n)`` over a BGZF file, inflated shard-parallel.

    BGZF members are independent deflate streams, so the compressed file
    splits at block boundaries (:func:`bgzf.scan_block_spans`) into
    shards of ~``VCTPU_IO_SHARD_BYTES`` decompressed bytes each, inflated
    on the IO worker pool and reassembled strictly in file order — the
    decompressed byte stream is identical to a serial ``gzip.open`` read,
    so chunk boundaries (and therefore journal resume identity) cannot
    depend on the worker count. Raises ``ValueError`` when the file is
    not cleanly BGZF-framed (plain gzip): callers fall back to the serial
    stream.
    """

    def __init__(self, path: str, pool, profiler=None, spans=None):
        from variantcalling_tpu.io import bgzf as bgzf_mod

        size = os.path.getsize(path)
        self.path = str(path)
        self._mm = (np.memmap(path, dtype=np.uint8, mode="r")
                    if size else np.empty(0, dtype=np.uint8))
        if spans is None:
            spans = bgzf_mod.scan_block_spans(self._mm) if size else []
            if spans is None:
                raise ValueError(f"{path}: not BGZF-framed")
        # ``spans`` given: a SUBSET of the member chain — the rank-span
        # window (docs/scaleout.md) inflates only its share of the file
        groups = bgzf_mod.group_spans(spans,
                                      knobs.get_int("VCTPU_IO_SHARD_BYTES"))
        from variantcalling_tpu.parallel.pipeline import imap_ordered

        self._profiler = profiler
        self._shards = imap_ordered(pool, self._inflate, groups,
                                    window=pool.threads + 2)
        self._buf = bytearray()
        self._eof = False

    def _inflate(self, spans) -> bytes:
        from variantcalling_tpu.io import bgzf as bgzf_mod
        from variantcalling_tpu.parallel.pipeline import retry_transient
        from variantcalling_tpu.utils import faults

        def attempt() -> bytes:
            # injection point "io.shard_decompress": inflate is a pure
            # function of the mapped bytes, so a transient error here is
            # always safely retryable; a persistent one propagates through
            # the future and fails the run cleanly
            faults.check("io.shard_decompress")
            return bgzf_mod.inflate_spans(self._mm, spans)

        if self._profiler is None:
            return retry_transient(attempt, f"bgzf shard inflate ({self.path})")
        t0 = time.perf_counter()  # vctpu-lint: disable=VCT006 — obs per-worker attribution
        out = retry_transient(attempt, f"bgzf shard inflate ({self.path})")
        worker = threading.current_thread().name.rsplit("-", 1)[-1]
        self._profiler.stage(f"inflate.{worker}").add_work(
            time.perf_counter() - t0,  # vctpu-lint: disable=VCT006 — obs per-worker attribution
            bytes_in=sum(s[1] for s in spans), bytes_out=len(out))
        return out

    def read(self, n: int) -> bytes:
        while len(self._buf) < n and not self._eof:
            nxt = next(self._shards, None)
            if nxt is None:
                self._eof = True
                break
            self._buf += nxt
        out = bytes(self._buf[:n])
        del self._buf[:n]
        return out

    def close(self) -> None:
        self._shards.close()
        self._buf.clear()
        self._mm = None


class _MemberStream:
    """Serial ``read(n)`` over a run of BGZF members (one rank's suffix
    of the member chain) — the ``VCTPU_IO_THREADS=1`` sibling of
    :class:`_ParallelBgzfStream` for the rank-span window."""

    def __init__(self, mm, spans):
        self._mm = mm
        self._spans = spans
        self._i = 0
        self._buf = bytearray()

    def read(self, n: int) -> bytes:
        from variantcalling_tpu.io import bgzf as bgzf_mod

        while len(self._buf) < n and self._i < len(self._spans):
            j = min(self._i + 16, len(self._spans))
            self._buf += bgzf_mod.inflate_spans(self._mm,
                                                self._spans[self._i:j])
            self._i = j
        out = bytes(self._buf[:n])
        del self._buf[:n]
        return out

    def close(self) -> None:
        self._buf.clear()
        self._i = len(self._spans)


class _SpanGzWindow:
    """File-like ``read(n)`` serving ONE rank's line-aligned window of a
    BGZF file's decompressed stream (the docs/scaleout.md partition
    rule).

    The window is ``[cut(t_lo), cut(t_hi))`` where ``cut(u)`` is the
    smallest line-start position >= ``u``: the position after the first
    newline at offset >= ``u - 1``, clamped to the record region
    ``[h, total]`` (the header always ends at a line start, so rank 0's
    window begins exactly at ``h``). Adjacent ranks compute the SAME cut
    for their shared target, so the windows partition the record region
    exactly — no record is lost or duplicated, whatever the BGZF block
    layout. The inner stream starts at the member holding the first
    byte the window needs, so a rank inflates only ~its share (plus the
    members its boundary lines straddle).
    """

    def __init__(self, inner, base: int, t_lo: int, t_hi: int,
                 h: int, total: int):
        self._inner = inner
        self._buf = bytearray()
        self._buf_abs = base  # absolute offset of _buf[0]
        self._inner_eof = False
        self._t_lo, self._t_hi = t_lo, t_hi
        self._h, self._total = h, total
        self._start: int | None = None  # cut(t_lo), resolved lazily
        self._end: int | None = None  # cut(t_hi)

    def start(self) -> int:
        """Absolute decompressed offset of the window's first byte —
        ``cut(t_lo)`` — resolving (and discarding the pre-window prefix)
        eagerly. The reader uses it as the base for the per-chunk input
        end offsets the elastic re-cut consumes."""
        self._resolve_start()
        return self._start

    def _resolve_start(self) -> None:
        if self._start is not None:
            return
        self._start = self._cut(self._t_lo)
        if self._t_hi >= self._total:
            self._end = self._total
        while self._buf_abs < self._start:
            if not self._buf:
                if not self._more():
                    break
                continue
            self._drop(min(len(self._buf),
                           self._start - self._buf_abs))

    def _more(self) -> bool:
        if self._inner_eof:
            return False
        block = self._inner.read(4 << 20)
        if not block:
            self._inner_eof = True
            return False
        self._buf += block
        return True

    def _drop(self, n: int) -> None:
        del self._buf[:n]
        self._buf_abs += n

    def _cut(self, t: int) -> int:
        """``cut(t)``, buffering inner bytes as needed; inner EOF clamps
        to the end of the stream."""
        if t <= self._h:
            return self._h
        if t >= self._total:
            return self._total
        probe = t - 1
        if probe < self._buf_abs:
            # the probe byte is already consumed — only possible when
            # this cut coincides with the (already resolved) start cut:
            # no newline separates the two targets, or cut(t_lo) would
            # have stopped earlier
            return self._start if self._start is not None else self._buf_abs
        while True:
            start_idx = probe - self._buf_abs
            if start_idx < len(self._buf):
                nl = self._buf.find(b"\n", start_idx)
                if nl >= 0:
                    return self._buf_abs + nl + 1
                probe = self._buf_abs + len(self._buf)
            if not self._more():
                return self._buf_abs + len(self._buf)  # EOF mid-final-line

    def read(self, n: int) -> bytes:
        self._resolve_start()
        out = bytearray()
        while len(out) < n:
            if self._end is not None and self._buf_abs >= self._end:
                break
            if not self._buf and not self._more():
                break
            avail = len(self._buf)
            if self._end is None:
                # end unknown: everything strictly before t_hi - 1 is
                # in-window; once the buffer reaches the probe byte,
                # resolve the end cut (which may buffer further — the
                # final line can straddle members)
                if self._buf_abs + avail > self._t_hi - 1:
                    self._end = self._cut(self._t_hi)
                    continue
                take = avail
            else:
                take = min(avail, self._end - self._buf_abs)
            take = min(take, n - len(out))
            if take <= 0:
                break
            out += self._buf[:take]
            self._drop(take)
        return bytes(out)

    def close(self) -> None:
        self._inner.close()


class VcfChunkReader:
    """Line-aligned chunked native VCF ingest for the streaming executor.

    Iterating yields :class:`VariantTable` chunks in file order, each
    parsed by the same native scanner + table assembly the whole-file path
    uses (so per-chunk tables are indistinguishable from row-slices of the
    whole-file table). Sources:

    - plain ``.vcf``: a memory map, sliced at line boundaries — the file
      never fully materializes in anonymous memory, so peak RSS does not
      scale with input size;
    - ``.gz``/``.bgz``: streamed decompression (zlib releases the GIL), one
      independent bytes buffer per chunk with partial-line carry — again
      O(chunk) resident, not O(file).

    With ``VCTPU_IO_THREADS`` > 1 (default: cpu count) ingest goes
    PARALLEL (docs/streaming_executor.md "Parallel host IO"): BGZF input
    inflates shard-parallel (:class:`_ParallelBgzfStream`) and chunk
    PARSE — the dominant ingest cost on plain text too — fans out over
    the IO worker pool, reassembled into canonical sequence order before
    the tables leave the iterator. Chunk boundaries are computed by the
    same serial rules either way, so the yielded chunk sequence (and the
    journal resume identity) is byte-identical at every worker count.

    One-shot: the underlying stream is consumed by iteration. Requires
    the native library (callers gate on ``native.available()``); a
    mid-stream scan failure raises rather than silently degrading.
    """

    def __init__(self, path: str, chunk_bytes: int = 0,
                 io_threads: int | None = None, profiler=None,
                 rank_span: tuple[int, int] | None = None,
                 span_targets: tuple[int, int] | None = None):
        from variantcalling_tpu import native
        from variantcalling_tpu.parallel.pipeline import resolve_io_threads

        if not native.available():
            raise RuntimeError("VcfChunkReader requires the native engine")
        self.path = str(path)
        # rank-partitioned ingest (docs/scaleout.md): ``(rank, ranks)``
        # restricts this reader to ONE contiguous line-aligned span of
        # the record region — the deterministic cut rule shared with
        # every other rank, so the spans partition the file exactly
        self._rank_span: tuple[int, int] | None = None
        if rank_span is not None and int(rank_span[1]) > 1:
            r, nr = int(rank_span[0]), int(rank_span[1])
            if not 0 <= r < nr:
                raise ValueError(f"rank_span {rank_span!r} out of range")
            self._rank_span = (r, nr)
        # elastic spans (docs/scaleout.md "Elastic membership"): absolute
        # decompressed-byte targets ``[t_lo, t_hi)``. The rank fractions
        # above are the special case ``t = h + body*r//n``; the SAME cut
        # rule maps ANY monotone target sequence to an exact line-aligned
        # partition, so re-cut/stolen spans keep the byte-parity contract
        self._span_targets: tuple[int, int] | None = None
        if span_targets is not None:
            lo, hi = int(span_targets[0]), int(span_targets[1])
            if hi < lo:
                raise ValueError(f"span_targets {span_targets!r} inverted")
            self._span_targets = (lo, hi)
            if self._rank_span is not None:
                raise ValueError("rank_span and span_targets are exclusive")
        #: decompressed bytes of this reader's span (None: whole file) —
        #: the heartbeat's progress denominator for rank runs
        self.span_bytes: int | None = None
        #: absolute decompressed END offset of every chunk boundary this
        #: reader computed so far (skipped chunks included, indexed by
        #: chunk sequence number) — the committer journals it as
        #: ``in_end`` so an elastic re-cut can split a dead span at the
        #: last journaled boundary (parallel/elastic.py)
        self.chunk_ends: list[int] = []
        # arg beats the env knob beats the (test-patchable) module
        # default; resolved here, not at import, so a malformed value is
        # caught by run()'s up-front knobs.validate_all() instead of an
        # import-time traceback
        env_chunk = knobs.get_int("VCTPU_STREAM_CHUNK_BYTES") \
            if knobs.raw("VCTPU_STREAM_CHUNK_BYTES") is not None else None
        self.chunk_bytes = int(chunk_bytes) or env_chunk or STREAM_CHUNK_BYTES
        self.io_threads = (resolve_io_threads() if io_threads is None
                          else max(1, int(io_threads)))
        self.profiler = profiler
        self._pool = None
        self._pool_shared = False
        #: chunks to advance WITHOUT parsing (journal resume: their output
        #: bytes are already committed). Boundaries are computed exactly as
        #: for parsed chunks, so the continuation is byte-faithful.
        self._skip = 0
        self._gz = self.path.endswith((".gz", ".bgz"))
        self._mm: np.ndarray | None = None
        self._fh = None
        self._pending = b""
        if self._gz and (self._rank_span is not None
                         or self._span_targets is not None):
            # rank-span gz ingest: member-mapped window (BGZF only)
            try:
                self._init_gz_span()
            except BaseException:
                self.close()
                raise
        elif self._gz:
            # a failing header scan (e.g. a persistent shard-inflate error
            # surfacing through the parallel stream) must release the
            # already-started pool workers — close() is unreachable from
            # callers when the constructor itself raises
            try:
                self._fh = self._open_gz_stream()
                self.header, first_off, head = self._scan_gz_header(self._fh)
                self._pending = head[first_off:]
                self._gz_base = first_off  # chunk-end offset base
            except BaseException:
                self.close()
                raise
        else:
            size = os.path.getsize(self.path)
            self._mm = (np.memmap(self.path, dtype=np.uint8, mode="r")
                        if size else np.empty(0, dtype=np.uint8))
            cap = 1 << 20
            while True:
                head = bytes(memoryview(self._mm[: min(cap, size)]))
                header, first_off = parse_header_bytes(head)
                if (first_off < len(head) and head[first_off : first_off + 1] != b"#") \
                        or cap >= size:
                    break
                cap *= 8
            self.header = header
            self._first_off = first_off
            self._span_lo, self._span_hi = first_off, size
            if self._rank_span is not None:
                self._span_lo, self._span_hi = self._mm_span_bounds(size)
                self.span_bytes = self._span_hi - self._span_lo
            elif self._span_targets is not None:
                lo, hi = self._span_targets
                self._span_lo = self._mm_newline_cut(lo, size)
                self._span_hi = max(self._span_lo,
                                    self._mm_newline_cut(hi, size))
                self.span_bytes = self._span_hi - self._span_lo

    def _scan_gz_header(self, fh) -> tuple:
        """Read the VCF header off a decompressed-byte stream — the ONE
        gz header-scan rule (read ``chunk_bytes`` windows until a record
        line begins or the stream ends), shared by the whole-file and
        rank-span constructors so the two can never parse different
        headers for the same file. Returns ``(header, first_off, head)``
        — ``head[first_off:]`` is the already-read record remainder."""
        head = b""
        while True:
            block = fh.read(self.chunk_bytes)
            head += block
            header, first_off = parse_header_bytes(head)
            if not block or (first_off < len(head)
                             and head[first_off:first_off + 1] != b"#"):
                break
        return header, first_off, head

    def _mm_newline_cut(self, u: int, size: int) -> int:
        """The smallest line-start position >= ``u`` (the rank-span cut
        rule): the position after the first newline at index >= u - 1,
        clamped to the record region. The SAME rule every rank applies,
        so adjacent spans meet exactly."""
        if u <= self._first_off:
            return self._first_off
        if u >= size:
            return size
        pos = u - 1
        probe = 1 << 16
        while pos < size:
            w = self._mm[pos: min(pos + probe, size)]
            hits = np.flatnonzero(w == 0x0A)
            if len(hits):
                return min(pos + int(hits[0]) + 1, size)
            pos += len(w)
            probe *= 8
        return size

    def _mm_span_bounds(self, size: int) -> tuple[int, int]:
        r, n_ranks = self._rank_span
        body = size - self._first_off
        lo = self._mm_newline_cut(self._first_off + body * r // n_ranks,
                                  size)
        hi = self._mm_newline_cut(
            self._first_off + body * (r + 1) // n_ranks, size)
        return lo, max(lo, hi)

    def _init_gz_span(self) -> None:
        """Rank-span ingest of a BGZF input: map the member chain, parse
        the header with a short serial inflate from the file start, then
        serve this rank's line-aligned window of the decompressed stream
        (:class:`_SpanGzWindow`) starting at the member that holds the
        window's first needed byte. Plain single-member gzip has no
        member split points — rank partitioning refuses it loudly
        (EngineError, exit 2) rather than silently re-inflating the
        whole prefix per rank."""
        from variantcalling_tpu.engine import EngineError
        from variantcalling_tpu.io import bgzf as bgzf_mod

        size = os.path.getsize(self.path)
        mm = (np.memmap(self.path, dtype=np.uint8, mode="r")
              if size else np.empty(0, dtype=np.uint8))
        spans = bgzf_mod.scan_block_spans(mm) if size else []
        if spans is None:
            raise EngineError(
                f"{self.path}: rank-partitioned ingest needs BGZF-framed "
                "input (plain gzip is one indivisible deflate stream) — "
                "re-compress with bgzip/the BGZF writer, or run "
                "single-rank (docs/scaleout.md)")
        with gzip.open(self.path, "rb") as fh:
            self.header, first_off, _ = self._scan_gz_header(fh)
        h = first_off
        total = int(sum(s[2] for s in spans))
        if self._span_targets is not None:
            # elastic span: explicit absolute targets, clamped to the
            # record region — the rank fractions below are the special
            # case the coordinator's initial plan reproduces exactly
            t_lo = max(h, min(self._span_targets[0], total))
            t_hi = max(t_lo, min(self._span_targets[1], total))
        else:
            r, n_ranks = self._rank_span
            body = max(0, total - h)
            t_lo = h + body * r // n_ranks
            t_hi = h + body * (r + 1) // n_ranks
        self.span_bytes = max(0, t_hi - t_lo)
        # first decompressed byte the window needs: the line-start probe
        # at t_lo - 1 (or the header end, for rank 0's window)
        probe = t_lo - 1 if t_lo > h else h
        probe = max(0, min(probe, max(total - 1, 0)))
        cum = 0
        m_lo = len(spans)
        for i, s in enumerate(spans):
            if cum + s[2] > probe:
                m_lo = i
                break
            cum += s[2]
        tail = spans[m_lo:]
        if self.io_threads > 1 and tail:
            inner = _ParallelBgzfStream(self.path, self._ensure_pool(),
                                        profiler=self.profiler, spans=tail)
        else:
            inner = _MemberStream(mm, tail)
        self._fh = _SpanGzWindow(inner, cum, t_lo, t_hi, h, total)
        self._pending = b""

    def _open_gz_stream(self):
        """The decompressed-byte source for ``.gz`` input: shard-parallel
        BGZF inflate when the IO pool is on and the file is BGZF-framed,
        the serial gzip stream otherwise (plain single-member gzip has no
        split points). Both yield the identical byte stream."""
        if self.io_threads > 1:
            try:
                return _ParallelBgzfStream(self.path, self._ensure_pool(),
                                           profiler=self.profiler)
            except ValueError:
                pass  # not BGZF-framed: one deflate stream, serial inflate
        return gzip.open(self.path, "rb")

    def _ensure_pool(self):
        if self._pool is None:
            from variantcalling_tpu.parallel.pipeline import IoPool

            self._pool = IoPool(self.io_threads)
        return self._pool

    def shared_pool(self):
        """The run-scoped IO pool, marked EXTERNALLY SHARED: the streaming
        executor hands it to work that outlives ingest (the chunk_worker
        fan-out and the writeback compress stage), so iteration exhaustion
        must no longer shut it down — a tail-chunk compress submitted to a
        dead pool would block forever. The run owner's :meth:`close` (in
        its teardown finally, after the pipeline drains) tears it down."""
        self._pool_shared = True
        return self._ensure_pool()

    def _close_stream(self) -> None:
        """Release the input stream only (idempotent)."""
        if self._fh is not None:
            try:
                self._fh.close()
            except OSError:
                pass
            self._fh = None

    def close(self) -> None:
        """Release the IO pool and the input stream (idempotent). Full
        unshared iteration closes implicitly; error paths and pool-sharing
        run owners call this so abandoned runs never accumulate idle pool
        workers."""
        if self._pool is not None:
            self._pool.shutdown()
            self._pool = None
        self._close_stream()

    def skip(self, n_chunks: int) -> None:
        """Advance the first ``n_chunks`` chunk boundaries without parsing
        them (journal resume — their rendered bytes are already on disk).
        Must be called before iteration starts."""
        self._skip = max(0, int(n_chunks))

    def chunk_end(self, seq: int) -> int | None:
        """Absolute decompressed end offset of chunk ``seq`` (``None``
        before its boundary is computed). Boundaries are computed during
        ingest, which strictly precedes the chunk's commit, so the
        committer's lookup for the chunk it just wrote always lands."""
        return self.chunk_ends[seq] if 0 <= seq < len(self.chunk_ends) \
            else None

    def _parse_chunk(self, buf_np: np.ndarray, lazy_buf) -> VariantTable:
        from variantcalling_tpu import native
        from variantcalling_tpu.parallel.pipeline import retry_transient
        from variantcalling_tpu.utils import faults

        def attempt() -> VariantTable:
            # injection point "io.chunk_read": a transient IO error here is
            # retried (parse is a pure function of the already-read buffer,
            # so a retry is always safe)
            faults.check("io.chunk_read")
            parsed = native.vcf_parse(buf_np, len(self.header.samples))
            if parsed is None:
                raise RuntimeError(f"native VCF scan failed mid-stream in {self.path}")
            return _table_from_parsed(parsed, self.header, lazy_buf, buf_np,
                                      drop_format=False)

        return retry_transient(attempt, f"chunk read ({self.path})")

    def iter_raw(self):
        """Raw ``(buf_np, lazy_buf)`` chunk buffers in canonical chunk
        order, WITHOUT parsing — the zero-wait chunk feed (ROADMAP item
        4). The streaming executor's pooled layout maps its whole
        per-chunk body (parse -> fused featurize+score -> render) over
        these on the IO pool, so a chunk is parsed immediately before it
        scores inside ONE task: no parsed table ever sits in a queue
        between a parse worker and a score worker (the
        ``score_stage.wait`` critical-path edge that dominated
        BENCH_r12's p95). Boundaries are the same serial rule as
        :meth:`__iter__` — byte parity and the journal resume identity
        are unchanged. gz inputs still inflate shard-parallel inside the
        raw generator. One-shot, like iteration; the same close
        semantics apply (shared pools outlive exhaustion).
        """
        raw = self._raw_gz() if self._gz else self._raw_mm()
        try:
            yield from raw
        finally:
            if self._pool_shared:
                self._close_stream()
            else:
                self.close()

    def parse_chunk(self, buf_np: np.ndarray, lazy_buf) -> VariantTable:
        """Parse one raw chunk buffer (``iter_raw``) into a
        :class:`VariantTable` — the same native scan + per-worker
        ``parse.wN`` attribution the internal pooled parse uses, exposed
        for the executor's fused per-chunk body."""
        return self._parse_worker((buf_np, lazy_buf))

    def __iter__(self):
        raw = self._raw_gz() if self._gz else self._raw_mm()
        if self.io_threads <= 1:
            for buf_np, lazy_buf in raw:
                yield self._parse_chunk(buf_np, lazy_buf)
            return
        # parallel chunk parse: the native scan releases the GIL, so
        # chunks genuinely parse concurrently on the IO pool; the ordered
        # window reassembles them into canonical sequence order before
        # they leave the iterator, so downstream consumers (the stage
        # pipeline, the journal) see exactly the serial chunk stream
        from variantcalling_tpu.parallel.pipeline import imap_ordered

        try:
            yield from imap_ordered(self._ensure_pool(), self._parse_worker,
                                    raw, window=self.io_threads + 1)
        finally:
            if self._pool_shared:
                # the pool outlives ingest (shared with the compress stage
                # and the chunk fan-out); the run owner shuts it down
                self._close_stream()
            else:
                self.close()

    def _parse_worker(self, raw: tuple) -> VariantTable:
        buf_np, lazy_buf = raw
        if self.profiler is None:
            return self._parse_chunk(buf_np, lazy_buf)
        t0 = time.perf_counter()  # vctpu-lint: disable=VCT006 — obs per-worker attribution
        table = self._parse_chunk(buf_np, lazy_buf)
        worker = threading.current_thread().name.rsplit("-", 1)[-1]
        self.profiler.stage(f"parse.{worker}").add_work(
            time.perf_counter() - t0,  # vctpu-lint: disable=VCT006 — obs per-worker attribution
            bytes_in=len(buf_np), records=len(table))
        return table

    def _raw_mm(self):
        """(buf_np, lazy_buf) chunk buffers in file order (plain text):
        the SAME boundary rule at every ``VCTPU_IO_THREADS`` setting.
        A rank-span reader iterates only its line-aligned span — the
        chunk rule applies to the span's bytes exactly as it would to a
        standalone file (chunk boundaries never change output bytes;
        they only shape the rank-local journal)."""
        mm = self._mm
        n = self._span_hi
        off = self._span_lo
        while off < n:
            end = min(off + self.chunk_bytes, n)
            if end < n:
                # align to the next newline (probe window grows for the
                # pathological all-one-line case)
                probe = 1 << 16
                while True:
                    w = mm[end: min(end + probe, n)]
                    hits = np.flatnonzero(w == 0x0A)
                    if len(hits):
                        end = end + int(hits[0]) + 1
                        break
                    if end + probe >= n:
                        end = n
                        break
                    probe *= 8
            self.chunk_ends.append(end)
            if self._skip > 0:
                self._skip -= 1
            else:
                view = mm[off:end]
                yield view, view
            off = end

    def _raw_gz(self):
        """(buf_np, lazy_buf) chunk buffers from the decompressed stream —
        the boundary rule reads fixed-size windows off ``self._fh``, so it
        is identical whether the stream is the serial gzip reader or the
        shard-parallel BGZF inflater."""
        # absolute offset of the next unconsumed decompressed byte: the
        # header end for whole-file ingest, cut(t_lo) for a span window —
        # chunk_ends advances from it by each chunk's raw length
        pos = (self._fh.start() if isinstance(self._fh, _SpanGzWindow)
               else self._gz_base)
        carry = self._pending
        self._pending = b""
        while True:
            block = self._fh.read(self.chunk_bytes)
            if not block:
                break
            block = carry + block
            cut = block.rfind(b"\n")
            if cut < 0:
                carry = block
                continue
            carry = block[cut + 1 :]
            chunk = block[: cut + 1]
            pos += len(chunk)
            self.chunk_ends.append(pos)
            if self._skip > 0:
                self._skip -= 1
                continue
            yield np.frombuffer(chunk, dtype=np.uint8), chunk
        if carry:
            pos += len(carry)
            self.chunk_ends.append(pos)
            if self._skip > 0:
                self._skip -= 1
            else:
                yield np.frombuffer(carry, dtype=np.uint8), carry
        self._fh.close()


def scan_record_region(path: str) -> tuple[int, int]:
    """``(header_end, total_size)`` of a VCF in DECOMPRESSED bytes — the
    target domain the elastic coordinator cuts spans over
    (``parallel/elastic.py``). The header-end rule matches the chunk
    readers' (``parse_header_bytes`` over a growing prefix), so the
    coordinator's span targets and every worker's cuts agree byte for
    byte. BGZF totals come from the member index (``scan_block_spans``
    isize sum) without inflating the file; plain single-member gzip has
    no split points and is refused loudly, exactly like rank-span
    ingest."""
    path = str(path)
    if path.endswith((".gz", ".bgz")):
        from variantcalling_tpu.engine import EngineError
        from variantcalling_tpu.io import bgzf as bgzf_mod

        size = os.path.getsize(path)
        mm = (np.memmap(path, dtype=np.uint8, mode="r") if size
              else np.empty(0, dtype=np.uint8))
        spans = bgzf_mod.scan_block_spans(mm) if size else []
        if spans is None:
            raise EngineError(
                f"{path}: span-partitioned ingest needs BGZF-framed "
                "input (plain gzip is one indivisible deflate stream) — "
                "re-compress with bgzip/the BGZF writer, or run "
                "single-rank (docs/scaleout.md)")
        total = int(sum(s[2] for s in spans))
        head = b""
        with gzip.open(path, "rb") as fh:
            while True:
                block = fh.read(STREAM_CHUNK_BYTES)
                head += block
                _header, first_off = parse_header_bytes(head)
                if not block or (first_off < len(head)
                                 and head[first_off:first_off + 1] != b"#"):
                    break
        return first_off, total
    size = os.path.getsize(path)
    mm = (np.memmap(path, dtype=np.uint8, mode="r") if size
          else np.empty(0, dtype=np.uint8))
    cap = 1 << 20
    while True:
        head = bytes(memoryview(mm[: min(cap, size)]))
        _header, first_off = parse_header_bytes(head)
        if (first_off < len(head) and head[first_off:first_off + 1] != b"#") \
                or cap >= size:
            break
        cap *= 8
    return first_off, size


def format_qual(q: float) -> str:
    if q is None or (isinstance(q, float) and np.isnan(q)):
        return MISSING
    if float(q) == int(q):
        return str(int(q))
    return f"{q:g}"


def write_vcf(
    path: str,
    table: VariantTable,
    new_filters: np.ndarray | None = None,
    extra_info: dict[str, np.ndarray] | None = None,
    sample_overrides: dict[int, np.ndarray] | None = None,
    fmt_override: np.ndarray | None = None,
    index: bool = True,
    verbatim_core: bool = False,
) -> None:
    """Write a VariantTable back to VCF, rewriting only the requested columns.

    - ``new_filters``: object array replacing the FILTER column.
    - ``extra_info``: info-key -> per-record value (np.nan/None skips a record;
      ``True`` writes a bare flag). Appended to the existing INFO string.
    - ``sample_overrides``: sample index -> object array of replacement
      sample strings; ``fmt_override`` replaces the FORMAT column.
    - ``index``: for ``.gz`` outputs, also build the sibling ``.tbi``
      (io/tabix) so htslib tools can consume the file directly.
    - ``verbatim_core``: caller asserts CHROM..QUAL were NOT edited since
      read; record assembly then runs in the native engine by splicing new
      FILTER/INFO between byte spans of the original buffer (the filter
      pipeline's writeback hot path). Ignored when the native library or
      parse buffer is unavailable.
    """
    if str(path).endswith(".gz"):
        from variantcalling_tpu.io.bgzf import BgzfWriter

        opener = lambda p, _mode: BgzfWriter(p)  # noqa: E731 — tabix-compatible blocks
    else:
        opener = open
    # tail fast path: FORMAT/sample columns come verbatim from the original
    # byte buffer (never materialized => never edited); all eight core
    # columns are rebuilt from the (possibly caller-edited) column arrays,
    # so in-place edits to chrom/pos/qual/... are always honored.
    fast = (
        table.aux is not None
        and table.aux.buf is not None
        and fmt_override is None
        and sample_overrides is None
        and not table.format_materialized
    )
    if not fast:
        table.materialize_format()  # slow path renders FORMAT/sample strings per record
    if fast:
        with opener(path, "wb") as out:
            for line in table.header.lines:
                out.write((line + "\n").encode())
            out.write((table.header.column_header() + "\n").encode())
            done = _write_assembled_native(out, table, new_filters, extra_info) \
                if verbatim_core else False
            if not done:
                _write_records_fast(out, table, new_filters, extra_info)
        if index and str(path).endswith(".gz"):
            from variantcalling_tpu.io.tabix import build_tabix_index

            try:
                build_tabix_index(str(path))
            except (ValueError, OSError):
                pass
        return
    with opener(path, "wt") as out:
        for line in table.header.lines:
            out.write(line + "\n")
        out.write(table.header.column_header() + "\n")
        n = len(table)
        for i in range(n):
            info_s = table.info[i]
            if extra_info:
                parts = [] if info_s in (MISSING, "", None) else [info_s]
                for k, vals in extra_info.items():
                    v = vals[i]
                    if v is None or (isinstance(v, float) and np.isnan(v)):
                        continue
                    if v is True:
                        parts.append(k)
                    elif isinstance(v, (float, np.floating)):
                        parts.append(f"{k}={float(v):g}")
                    else:
                        parts.append(f"{k}={v}")
                info_s = ";".join(parts) if parts else MISSING
            filt_s = new_filters[i] if new_filters is not None else table.filters[i]
            cols = [
                table.chrom[i],
                str(table.pos[i]),
                table.vid[i],
                table.ref[i],
                table.alt[i],
                format_qual(table.qual[i]),
                filt_s,
                info_s,
            ]
            if table.fmt_keys is not None:
                cols.append(fmt_override[i] if fmt_override is not None else table.fmt_keys[i])
                for s in range(table.n_samples):
                    if sample_overrides and s in sample_overrides:
                        cols.append(sample_overrides[s][i])
                    else:
                        cols.append(table.sample_cols[i][s])
            out.write("\t".join(cols) + "\n")
    if index and str(path).endswith(".gz"):
        from variantcalling_tpu.io.tabix import build_tabix_index

        try:
            build_tabix_index(str(path))
        except (ValueError, OSError):
            pass  # unsorted/odd inputs: the VCF itself is still valid


def _format_extra_info_bytes(n: int, extra_info: dict) -> list[bytes]:
    """Per-record b";K=V" suffixes in dict key order (float columns vectorized)."""
    acc = np.full(n, b"", dtype="S1")
    for k, vals in (extra_info or {}).items():
        arr = np.asarray(vals)
        if arr.dtype.kind == "f":
            f64 = arr.astype(np.float64)
            joined = np.char.add((";" + k + "=").encode(), np.char.mod(b"%g", f64))
            acc = np.where(~np.isnan(f64), np.char.add(acc, joined), acc)
        else:
            kb = k.encode()
            part = []
            for i in range(n):
                v = vals[i]
                if v is None or (isinstance(v, float) and np.isnan(v)):
                    part.append(b"")
                elif v is True:
                    part.append(b";" + kb)
                else:
                    part.append(b";" + kb + b"=" + str(v).encode())
            acc = np.char.add(acc, np.asarray(part, dtype="S"))
    return acc.tolist()


def _format_qual_column(qual: np.ndarray) -> np.ndarray:
    """Vectorized format_qual over the whole column (object array of str)."""
    q = np.asarray(qual, dtype=np.float64)
    out = np.full(len(q), MISSING, dtype=object)
    ok = ~np.isnan(q)
    is_int = ok & (q == np.floor(q))
    out[is_int] = np.char.mod("%d", q[is_int].astype(np.int64))
    frac = ok & ~is_int
    out[frac] = np.char.mod("%g", q[frac])
    return out


def _encode_column_factorized(values, n: int) -> tuple[np.ndarray, np.ndarray]:
    """(byte buffer, (n+1,) offsets) for a low-cardinality string column.

    FILTER columns repeat a handful of values (PASS/LOW_SCORE/...), so a
    hash factorize + per-unique vectorized byte fill beats 1M per-record
    Python encodes ~10x on the writeback hot path. A
    :class:`FactorizedColumn` skips the factorize entirely."""
    if isinstance(values, FactorizedColumn):
        codes, uniques = values.codes, values.uniques
    else:
        import pandas as pd

        codes, uniques = pd.factorize(np.asarray(values, dtype=object), use_na_sentinel=False)
    # factorize normalizes None to float NaN — both mean "missing" (.)
    enc = [(MISSING if u is None or u == "" or (isinstance(u, float) and np.isnan(u))
            else str(u)).encode() for u in uniques]
    lens = np.fromiter((len(e) for e in enc), dtype=np.int64, count=len(enc))
    offs = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(lens[codes], out=offs[1:])
    buf = np.empty(int(offs[-1]), dtype=np.uint8)
    starts = offs[:-1]
    for ui, e in enumerate(enc):
        s = starts[codes == ui]
        eb = np.frombuffer(e, dtype=np.uint8)
        for j in range(len(e)):  # per-unique per-char: a few dozen fills total
            buf[s + j] = eb[j]
    return buf, offs


def _filter_info_blobs(table: VariantTable, new_filters, extra_info):
    """(filt_buf, filt_offs, sfx_buf, sfx_offs) for native record assembly.

    Shared by the whole-table writeback and the per-chunk streaming
    renderer so the two produce identical bytes by construction."""
    from variantcalling_tpu import native

    n = len(table)
    filters = new_filters if new_filters is not None else table.filters
    filt_buf, filt_offs = _encode_column_factorized(filters, n)

    # single float INFO column (the pipeline's TREE_SCORE writeback):
    # render ';KEY=%g' in the native engine; anything else falls back to
    # the generic per-record formatter
    sfx = None
    if extra_info and len(extra_info) == 1:
        (k, vals), = extra_info.items()
        arr = np.asarray(vals)
        if arr.dtype.kind == "f":
            sfx = native.format_float_info(arr, b";" + k.encode() + b"=")
    if sfx is not None:
        sfx_buf, sfx_offs = sfx
    else:
        suffix = _format_extra_info_bytes(n, extra_info) if extra_info else [b""] * n
        sfx_offs = np.zeros(n + 1, dtype=np.int64)
        np.cumsum(np.fromiter(map(len, suffix), dtype=np.int64, count=n), out=sfx_offs[1:])
        sfx_buf = np.frombuffer(b"".join(suffix), dtype=np.uint8)
    return filt_buf, filt_offs, sfx_buf, sfx_offs


def assemble_table_bytes(table: VariantTable, new_filters=None, extra_info=None,
                         out: np.ndarray | None = None) -> np.ndarray | None:
    """Render one table's record body as a uint8 array via the native
    engine (the streaming executor's per-chunk writeback stage). Returns
    None when the native engine or the parse buffer is unavailable —
    callers fall back to :func:`render_table_bytes_python`."""
    from variantcalling_tpu import native

    aux = table.aux
    if aux is None or aux.buf is None or not native.available():
        return None
    filt_buf, filt_offs, sfx_buf, sfx_offs = _filter_info_blobs(table, new_filters, extra_info)
    return native.vcf_assemble(
        aux.buf, aux.line_spans, aux.filter_spans, aux.info_spans, aux.tail_spans,
        filt_buf, filt_offs, sfx_buf, sfx_offs, out=out)


def render_table_bytes_python(table: VariantTable, new_filters=None,
                              extra_info=None) -> bytes:
    """Python twin of :func:`assemble_table_bytes` (same bytes as the
    per-record writer path), for engines without the native library."""
    sink = _io.BytesIO()
    _write_records_fast(sink, table, new_filters, extra_info)
    return sink.getvalue()


def _write_assembled_native(out, table: VariantTable, new_filters, extra_info) -> bool:
    """Native record assembly (verbatim CHROM..QUAL head; see write_vcf),
    streamed in record chunks through ONE reused output buffer — a
    whole-callset buffer would touch ~400 MB of fresh pages at 5M records
    and then sweep them again for the file write; chunking keeps the
    working set page-warm. Returns False (nothing written) when the
    native engine is unavailable."""
    from variantcalling_tpu import native

    aux = table.aux
    if aux is None or aux.buf is None or not native.available():
        return False
    n = len(table)
    filt_buf, filt_offs, sfx_buf, sfx_offs = _filter_info_blobs(table, new_filters, extra_info)

    # blob offsets are absolute, so chunk slices pass the full blobs with
    # an offsets window; spans slice to contiguous row ranges
    chunk = 1 << 20
    scratch: np.ndarray | None = None
    for lo in range(0, n, chunk):
        hi = min(lo + chunk, n)
        body = native.vcf_assemble(
            aux.buf,
            aux.line_spans[lo:hi],
            aux.filter_spans[lo:hi],
            aux.info_spans[lo:hi],
            aux.tail_spans[lo:hi],
            filt_buf,
            filt_offs[lo : hi + 1],
            sfx_buf,
            sfx_offs[lo : hi + 1],
            out=scratch,
        )
        if body is None:
            if lo == 0:
                return False  # nothing written yet: Python fallback
            # mid-stream failure (alloc/thread exhaustion in the engine):
            # finish rows [lo, n) through the per-record Python writer so
            # the output file is still complete and correct
            rest = np.arange(lo, n)
            _write_records_fast(
                out, table.subset(rest),
                new_filters[rest] if new_filters is not None else None,
                {k: np.asarray(v)[rest] for k, v in extra_info.items()} if extra_info else None)
            return True
        out.write(memoryview(body))
        base = body.base if isinstance(body.base, np.ndarray) else body
        scratch = base if base.ndim == 1 else None
    return True


def _write_records_fast(out, table: VariantTable, new_filters, extra_info) -> None:
    """Record writeback with the FORMAT/sample tail copied verbatim from the
    original buffer (NativeAux spans); the eight core columns are rebuilt
    from the live column arrays so caller edits are always written."""
    aux = table.aux
    bufb = aux.buf.tobytes()
    n = len(table)
    tails = aux.tail_spans.tolist()
    suffix = _format_extra_info_bytes(n, extra_info) if extra_info else None
    filters = new_filters if new_filters is not None else table.filters
    pos_s = np.char.mod("%d", table.pos)  # vectorized int formatting
    qual_s = _format_qual_column(table.qual)
    chrom, vid, ref, alt, info_col = table.chrom, table.vid, table.ref, table.alt, table.info
    chunks: list[bytes] = []
    for i in range(n):
        info = info_col[i]
        if suffix is not None and suffix[i]:
            sfx = suffix[i].decode()
            info = sfx[1:] if info == MISSING else info + sfx
        ta, tb = tails[i]
        tail = b"\t" + bufb[ta:tb] if tb > ta else b""
        line = "\t".join(
            (chrom[i], pos_s[i], vid[i], ref[i], alt[i], qual_s[i], filters[i], info)
        )
        chunks.append(line.encode() + tail + b"\n")
        if len(chunks) >= 16384:
            out.write(b"".join(chunks))
            chunks.clear()
    if chunks:
        out.write(b"".join(chunks))
