"""Content-addressed chunk-result cache — stop recomputing what heavy
traffic repeats (docs/caching.md).

Real request streams repeat: the same reference panels, the same exome
intervals, the same callset re-filtered with one knob changed. The
streaming executor's structure already makes chunk results pure —
chunk boundaries are a function of (input bytes, chunk_bytes), every
per-variant product is row-local, and the resume journal proves rendered
bytes are a pure function of (input span, scoring config). This module
promotes that proof from within one run (resume) to ACROSS runs and
requests: a bounded store of rendered chunk bodies keyed by

    ``<fingerprint[:16]>-<crc32(raw span)>-<len(raw span)>``

where the fingerprint is :func:`io.identity.fingerprint` over the SAME
``config`` dict the resume journal pins (engine, strategy, mesh/rank
layout, model/flags/files — scoring-relevant knobs ONLY, so an
io-thread or obs change still hits). Values are UNCOMPRESSED rendered
plain-text bodies plus their (records, pass) counts: a ``.gz`` run
recompresses replayed bodies through the live BGZF carry, so output
framing stays byte-identical to a cold run at any hit/miss interleaving.

Three tiers share the store machinery:

- **batch CLI** — :class:`DiskStore` under ``VCTPU_CACHE_DIR``:
  atomic per-entry write (tmp + ``os.replace``; a SIGKILL mid-write
  leaves only swept tmp garbage, never a torn entry), CRC-verified
  read (a poisoned/torn entry is evicted and recomputed — the cache can
  DEGRADE a run to cold, never corrupt it), mtime-LRU bound by
  ``VCTPU_CACHE_MAX_MB``;
- **vctpu serve** — an in-process :class:`MemoryStore` warm index
  shared across requests (:func:`resident_mode`), consulted before
  disk and warmed by disk hits;
- **rank-partitioned / elastic pod** — ONE shared store with
  PARTITION-AGNOSTIC keys (``identity.cache_identity`` strips the
  rank/span layout from the fingerprint): rendered record bytes are a
  pure function of (raw span, scoring config), never of which worker
  rendered them, so a re-cut or stolen elastic span warm-hits entries
  its dead predecessor published. Sibling workers share the directory
  safely — writes are atomic renames, eviction is best-effort.

Publication is **committed-prefix only**: workers STAGE computed
entries by chunk sequence number, and the sequenced committer publishes
them only after the chunk's bytes are in the partial file (and
journaled). A cancelled serve request, a failed run, or a SIGKILL
therefore never publishes an entry for bytes no output carried — the
warm index is exactly as the request found it.
"""

from __future__ import annotations

import os
import struct
import tempfile
import threading
import zlib
from collections import OrderedDict

from variantcalling_tpu import knobs, logger
from variantcalling_tpu.io import identity as identity_mod
from variantcalling_tpu.utils import degrade, faults

#: on-disk entry framing: magic, n_records, n_pass, body_len, body_crc32
_MAGIC = b"VCC1"
_HDR = struct.Struct("<4sIIQI")
ENTRY_SUFFIX = ".vcc"
_TMP_PREFIX = ".vcc_tmp_"
#: tmp files older than this are torn leftovers of a killed writer
_STALE_TMP_S = 300.0


def enabled() -> bool:
    """Opt-in (``VCTPU_CACHE=1``): default off so existing baselines,
    byte-parity suites and air-gapped runs are untouched."""
    return knobs.get_bool("VCTPU_CACHE")


def store_dir() -> str:
    d = knobs.get_str("VCTPU_CACHE_DIR")
    return d or os.path.join(os.path.expanduser("~"), ".cache", "vctpu",
                             "chunks")


def max_bytes() -> int:
    return knobs.get_int("VCTPU_CACHE_MAX_MB") << 20


def _encode(body: bytes, records: int, passed: int) -> bytes:
    return _HDR.pack(_MAGIC, records, passed, len(body),
                     zlib.crc32(body)) + body


def _decode(blob: bytes) -> tuple[bytes, int, int] | None:
    """Parse + verify one stored entry; ``None`` for ANYTHING suspicious
    (short read, bad magic, length mismatch, CRC mismatch) — the caller
    treats it as a miss and recomputes."""
    if len(blob) < _HDR.size:
        return None
    magic, records, passed, body_len, crc = _HDR.unpack_from(blob)
    if magic != _MAGIC or len(blob) != _HDR.size + body_len:
        return None
    body = blob[_HDR.size:]
    if zlib.crc32(body) != crc:
        return None
    return body, records, passed


class DiskStore:
    """One directory of ``<key>.vcc`` entries, LRU-bounded by mtime.

    Concurrency: safe for many processes (the pod tier gives each rank
    its own directory, but nothing breaks without that) — writes are
    atomic renames, reads tolerate concurrent eviction, and the bound
    enforcement treats every stat/remove as best-effort.
    """

    def __init__(self, root: str, bound: int):
        self.root = root
        self.bound = bound
        self._lock = threading.Lock()
        os.makedirs(root, exist_ok=True)
        self._sweep_tmp()

    def _path(self, key: str) -> str:
        return os.path.join(self.root, key + ENTRY_SUFFIX)

    def _sweep_tmp(self) -> None:
        """Collect torn tmp files a SIGKILLed writer left behind —
        age-gated so a live concurrent writer's in-flight tmp survives."""
        import time

        now = time.time()  # vctpu-lint: disable=VCT006 — stale-file age gate, not a measurement
        try:
            names = os.listdir(self.root)
        except OSError:
            return
        for name in names:
            if not name.startswith(_TMP_PREFIX):
                continue
            p = os.path.join(self.root, name)
            try:
                if now - os.stat(p).st_mtime > _STALE_TMP_S:
                    os.remove(p)
            except OSError:
                pass

    def get(self, key: str) -> tuple[bytes, int, int] | None:
        path = self._path(key)
        try:
            faults.check("cache.entry_read")
            with open(path, "rb") as fh:
                blob = fh.read()
        except FileNotFoundError:
            return None
        except OSError as e:
            degrade.record("chunk_cache.entry_read", e,
                           fallback="treated as a miss — chunk recomputed")
            return None
        ent = _decode(blob)
        if ent is None:
            # poisoned/torn entry: never serve it, never trust it again —
            # evict so the recomputed result can take the slot
            logger.warning("chunk cache: corrupt entry %s — evicted, "
                           "recomputing", path)
            try:
                os.remove(path)
            except OSError:
                pass
            return None
        try:
            os.utime(path)  # LRU touch
        except OSError:
            pass
        return ent

    def put(self, key: str, body: bytes, records: int, passed: int) -> None:
        fd, tmp = tempfile.mkstemp(prefix=_TMP_PREFIX, dir=self.root)
        try:
            # injection point "cache.entry_write": armed with a delay it
            # hangs HERE, mid-entry-write — the chaoshunt ``cache_torn``
            # class SIGKILLs the process in this window, leaving only the
            # tmp file (swept later), never a torn published entry
            faults.check("cache.entry_write")
            with os.fdopen(fd, "wb") as fh:
                fh.write(_encode(body, records, passed))
            os.replace(tmp, self._path(key))
        except BaseException:
            try:
                os.remove(tmp)
            except OSError:
                pass
            raise
        self._enforce_bound()

    def _enforce_bound(self) -> None:
        """Evict least-recently-USED (mtime — reads touch) entries until
        the directory fits the byte bound. Races with concurrent ranks/
        processes resolve to at-worst extra eviction, never corruption."""
        with self._lock:
            try:
                names = os.listdir(self.root)
            except OSError:
                return
            entries = []
            total = 0
            for name in names:
                if not name.endswith(ENTRY_SUFFIX):
                    continue
                p = os.path.join(self.root, name)
                try:
                    st = os.stat(p)
                except OSError:
                    continue
                entries.append((st.st_mtime, st.st_size, p))
                total += st.st_size
            if total <= self.bound:
                return
            for _, size, p in sorted(entries):
                try:
                    os.remove(p)
                except OSError:
                    continue
                total -= size
                if total <= self.bound:
                    break

    def stats(self) -> dict:
        try:
            names = os.listdir(self.root)
        except OSError:
            return {"entries": 0, "bytes": 0}
        n = b = 0
        for name in names:
            if name.endswith(ENTRY_SUFFIX):
                try:
                    b += os.stat(os.path.join(self.root, name)).st_size
                except OSError:
                    continue
                n += 1
        return {"entries": n, "bytes": b}


class MemoryStore:
    """Byte-bounded in-process LRU — the ``vctpu serve`` warm index.
    Entries are immutable bytes; all map/size state is lock-protected
    (requests look up from pooled worker threads)."""

    def __init__(self, bound: int):
        self.bound = bound
        self._lock = threading.Lock()
        self._entries: OrderedDict[str, tuple[bytes, int, int]] = OrderedDict()
        self._bytes = 0

    def get(self, key: str) -> tuple[bytes, int, int] | None:
        with self._lock:
            ent = self._entries.get(key)
            if ent is not None:
                self._entries.move_to_end(key)
            return ent

    def put(self, key: str, body: bytes, records: int, passed: int) -> None:
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= len(old[0])
            self._entries[key] = (body, records, passed)
            self._bytes += len(body)
            while self._bytes > self.bound and self._entries:
                _, (b, _k, _p) = self._entries.popitem(last=False)
                self._bytes -= len(b)

    def stats(self) -> dict:
        with self._lock:
            return {"entries": len(self._entries), "bytes": self._bytes}


#: resident warm index (vctpu serve): created on first use AFTER the
#: daemon opted in, shared across requests for the process lifetime
_RESIDENT = False
_MEMORY: MemoryStore | None = None
_MEMORY_LOCK = threading.Lock()

#: process-cumulative session tallies (serve status/debuggability);
#: updated under _MEMORY_LOCK at session finish
_TOTALS = {"sessions": 0, "hits": 0, "misses": 0, "bytes_saved": 0,
           "published": 0}


def resident_mode(on: bool = True) -> None:
    """Opt this process into the in-memory warm index (the serve daemon
    calls this at startup). Batch CLIs skip it: a one-shot run would
    only duplicate every rendered body in RAM."""
    global _RESIDENT
    with _MEMORY_LOCK:
        _RESIDENT = on


def _memory_store() -> MemoryStore | None:
    global _MEMORY
    with _MEMORY_LOCK:
        if not _RESIDENT:
            return None
        if _MEMORY is None:
            _MEMORY = MemoryStore(max_bytes())
        return _MEMORY


def resident_stats() -> dict:
    """Serve ``/status`` payload: warm-index size + cumulative traffic."""
    with _MEMORY_LOCK:
        out = dict(_TOTALS, enabled=enabled(), resident=_RESIDENT)
        mem = _MEMORY
    out["memory"] = mem.stats() if mem is not None else {"entries": 0,
                                                         "bytes": 0}
    return out


def reset_for_tests() -> None:
    global _RESIDENT, _MEMORY
    with _MEMORY_LOCK:
        _RESIDENT = False
        _MEMORY = None
        for k in _TOTALS:
            _TOTALS[k] = 0


class CacheSession:
    """One run's view over the stores: fingerprint-scoped keys, counted
    lookups, and committed-prefix publication.

    Thread contract: :meth:`key_of`/:meth:`get`/:meth:`stage` run on
    pooled chunk workers; :meth:`publish_up_to`/:meth:`discard`/
    :meth:`finish` run on the sequenced committer. Shared tallies and
    the staging map are lock-protected.
    """

    def __init__(self, fp: str, stores: list):
        self.fingerprint = fp
        self._fp16 = fp[:16]
        self._stores = stores  # consult order: memory (if any), disk
        self._lock = threading.Lock()
        self._staged: dict[int, tuple[str, object, int, int]] = {}
        self.hits = 0
        self.misses = 0
        self.bytes_saved = 0
        self.published = 0

    def key_of(self, raw) -> str:
        """Content address of one raw input span under this config.
        CRC32 (GIL-releasing, ~1 GB/s) + span length over the UNPARSED
        chunk bytes — the same span identity the resume journal's
        chunk-boundary argument rests on."""
        return (f"{self._fp16}-{zlib.crc32(raw) & 0xFFFFFFFF:08x}-"
                f"{len(raw)}")

    def get(self, key: str) -> tuple[bytes, int, int] | None:
        from variantcalling_tpu import obs

        for i, store in enumerate(self._stores):
            ent = store.get(key)
            if ent is None:
                continue
            body, records, passed = ent
            if i > 0 and self._stores and \
                    isinstance(self._stores[0], MemoryStore):
                # a disk hit warms the resident index for the NEXT request
                self._stores[0].put(key, bytes(body), records, passed)
            with self._lock:
                self.hits += 1
                self.bytes_saved += len(body)
            if obs.active():
                obs.counter("cache.hit").add(1)
                obs.counter("cache.bytes_saved").add(len(body))
            return body, records, passed
        with self._lock:
            self.misses += 1
        if obs.active():
            obs.counter("cache.miss").add(1)
        return None

    def stage(self, seq: int, key: str, body, records: int,
              passed: int) -> None:
        """Hold a computed entry until its chunk COMMITS. ``body`` may
        be an ndarray view; it is copied to bytes at publish time (the
        committer), never on the worker's hot path."""
        with self._lock:
            self._staged[seq] = (key, body, records, passed)

    def publish_up_to(self, seq: int) -> None:
        """Publish every staged entry whose chunk sequence number is
        ``<= seq`` — called by the committer AFTER those bytes reached
        the sink (and the journal, when journaling). Store failures
        degrade (entry dropped), never fail the run."""
        with self._lock:
            ready = sorted(s for s in self._staged if s <= seq)
            items = [(s, self._staged.pop(s)) for s in ready]
        for _s, (key, body, records, passed) in items:
            blob = body if isinstance(body, bytes) else bytes(body)
            for store in self._stores:
                try:
                    store.put(key, blob, records, passed)
                except OSError as e:
                    degrade.record(
                        "chunk_cache.entry_write", e, warn=True,
                        fallback="cache entry dropped — output unaffected")
            with self._lock:
                self.published += 1

    def discard(self) -> None:
        """Failure/cancellation path: drop everything unpublished — a
        dead request leaves the warm index exactly as it found it."""
        with self._lock:
            self._staged.clear()

    def stats(self) -> dict:
        with self._lock:
            return {"hits": self.hits, "misses": self.misses,
                    "bytes_saved": self.bytes_saved,
                    "published": self.published}

    def finish(self) -> None:
        """End-of-run bookkeeping: one ``cache`` obs event with the
        session's traffic, rolled into the process totals serve's
        ``/status`` reports."""
        from variantcalling_tpu import obs

        st = self.stats()
        with _MEMORY_LOCK:
            _TOTALS["sessions"] += 1
            _TOTALS["hits"] += st["hits"]
            _TOTALS["misses"] += st["misses"]
            _TOTALS["bytes_saved"] += st["bytes_saved"]
            _TOTALS["published"] += st["published"]
        if obs.active():
            obs.event("cache", "session", **st)


def open_session(config: dict, rank: int = 0,
                 ranks: int = 1) -> CacheSession | None:
    """The one constructor (``pipelines/filter_variants.py``): ``None``
    when the cache is off; otherwise a session over the resident memory
    index (serve) and/or the on-disk store. An unusable cache directory
    degrades to whatever stores remain — never fails the run.

    Keys are PARTITION-AGNOSTIC (``identity.cache_identity``): the
    rank/span layout is stripped from the fingerprint and every worker
    shares ONE store directory, so a re-cut or stolen elastic span
    warm-hits entries its dead predecessor published — on mm inputs the
    chunk-boundary recurrence makes a re-cut suffix re-key identically
    (docs/caching.md "Elastic pods"). The ``rank``/``ranks`` parameters
    remain for call-site symmetry; they no longer shape the key or the
    store path. Concurrent ranks on one DiskStore are safe by its
    atomic-rename + best-effort-evict design."""
    del rank, ranks  # partition-agnostic since the elastic-pods PR
    if not enabled():
        return None
    fp = identity_mod.fingerprint(identity_mod.cache_identity(config))
    stores: list = []
    mem = _memory_store()
    if mem is not None:
        stores.append(mem)
    root = store_dir()
    try:
        stores.append(DiskStore(root, max_bytes()))
    except OSError as e:
        degrade.record("chunk_cache.store_open", e, warn=True,
                       fallback="chunk cache disabled for this run"
                       if not stores else "in-memory warm index only")
    if not stores:
        return None
    return CacheSession(fp, stores)
