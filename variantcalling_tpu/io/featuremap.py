"""Featuremap VCF -> columnar per-read DataFrame (ugbio_featuremap parity).

A featuremap is a VCF with one record per supporting read of each candidate
SNV, carrying per-read evidence in INFO (X_SCORE, X_EDIST, X_LENGTH,
X_MAPQ, X_INDEX, X_READ_COUNT, X_FILTERED_COUNT, rq, ...). The reference's
``featuremap_to_dataframe`` (lpr/train_lib_prep_recalibration_model.py:
60-118 call sites) converts it to a parquet frame; here the conversion is
one columnar pass: numeric INFO keys become float columns, the rest become
strings, plus chrom/pos/ref/alt/qual/filter and reference trinucleotide
motif columns.
"""

from __future__ import annotations

import numpy as np
import pandas as pd

from variantcalling_tpu.io.vcf import MISSING, read_vcf


def featuremap_to_dataframe(
    featuremap_vcf: str,
    ref_fasta: str | None = None,
    flow_order: str = "TGCA",
    info_fields: list[str] | None = None,
    motif_length: int = 3,
) -> pd.DataFrame:
    """Columnar frame from a featuremap VCF; one row per record (= per read)."""
    table = read_vcf(featuremap_vcf)
    n = len(table)
    cols: dict[str, np.ndarray] = {
        "chrom": np.asarray(table.chrom),
        "pos": table.pos,
        "ref": np.asarray(table.ref),
        "alt": np.asarray([a.split(",")[0] for a in table.alt], dtype=object),
        "qual": np.nan_to_num(table.qual, nan=0.0),
        "filter": np.asarray(["PASS" if f in (MISSING, "") else f for f in table.filters], dtype=object),
    }

    # discover INFO keys from the header (or use the explicit list)
    keys = info_fields if info_fields is not None else list(table.header.infos)
    for key in keys:
        meta = table.header.infos.get(key, {})
        typ = meta.get("Type", "String")
        if typ in ("Integer", "Float"):
            cols[key.lower()] = table.info_field(key, dtype=np.float64, missing=np.nan)
        elif typ == "Flag":
            cols[key.lower()] = table.info_flag(key)
        else:
            vals = np.full(n, "", dtype=object)
            for i, s in enumerate(table.info):
                if s in (None, MISSING, ""):
                    continue
                for part in s.split(";"):
                    if part.startswith(key + "="):
                        vals[i] = part.split("=", 1)[1]
                        break
            cols[key.lower()] = vals

    if ref_fasta is not None:
        from variantcalling_tpu.featurize import gather_windows
        from variantcalling_tpu.io.fasta import FastaReader

        radius = motif_length
        with FastaReader(ref_fasta) as fa:
            windows = gather_windows(table, fa, radius=radius)
        bases = np.array(list("ACGTN"))
        left = ["".join(bases[w[:radius]]) for w in windows]
        right = ["".join(bases[w[radius + 1 :]]) for w in windows]
        cols["left_motif"] = np.asarray(left, dtype=object)
        cols["right_motif"] = np.asarray(right, dtype=object)
        cols["ref_motif"] = np.asarray(
            [l[-1] + r + rt[0] for l, r, rt in zip(left, cols["ref"], right)], dtype=object
        )
    return pd.DataFrame(cols)


NUMERIC_FEATURE_CANDIDATES = [
    "x_score",
    "x_edist",
    "x_length",
    "x_mapq",
    "x_index",
    "x_fc1",
    "x_fc2",
    "rq",
    "max_softclip_length",
]


def numeric_feature_columns(df: pd.DataFrame) -> list[str]:
    """The numeric per-read evidence columns present in a featuremap frame."""
    return [c for c in NUMERIC_FEATURE_CANDIDATES if c in df.columns and np.issubdtype(df[c].dtype, np.number)]
