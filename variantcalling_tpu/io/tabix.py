"""Tabix (.tbi) index writer for BGZF-compressed VCF/BED.

The reference indexes every compressed artifact by shelling out to
``tabix`` (bash/index_vcf_file.sh, compress_gvcf.py:214). This module
builds the index in-process over the framework's own BGZF layer, so
written ``.vcf.gz`` files remain drop-in consumable by htslib tools
(bcftools/IGV expect a sibling ``.tbi``).

Format per the tabix spec (SAMv1/tabix.pdf): BGZF-wrapped payload of
UCSC-binned chunk lists + a 16kb linear index, virtual file offsets =
(compressed block offset << 16) | in-block offset.
"""

from __future__ import annotations

import struct
import zlib

import numpy as np

from variantcalling_tpu.io.bgzf import BgzfWriter, compress_block

TBI_MAGIC = b"TBI\x01"
FMT_VCF = 2
FMT_BED = 0x10000  # generic, 0-based half-open
LINEAR_SHIFT = 14


def reg2bin(beg: int, end: int) -> int:
    """UCSC binning: smallest bin fully containing [beg, end) (0-based)."""
    end -= 1
    if beg >> 14 == end >> 14:
        return ((1 << 15) - 1) // 7 + (beg >> 14)
    if beg >> 17 == end >> 17:
        return ((1 << 12) - 1) // 7 + (beg >> 17)
    if beg >> 20 == end >> 20:
        return ((1 << 9) - 1) // 7 + (beg >> 20)
    if beg >> 23 == end >> 23:
        return ((1 << 6) - 1) // 7 + (beg >> 23)
    if beg >> 26 == end >> 26:
        return ((1 << 3) - 1) // 7 + (beg >> 26)
    return 0


def _iter_bgzf_blocks(path: str):
    """Yield (compressed_offset, uncompressed_bytes) per BGZF block."""
    with open(path, "rb") as fh:
        data = fh.read()
    off = 0
    n = len(data)
    while off < n:
        if data[off : off + 2] != b"\x1f\x8b":
            raise ValueError(f"{path}: not BGZF at offset {off}")
        xlen = struct.unpack_from("<H", data, off + 10)[0]
        xoff = off + 12
        bsize = None
        while xoff < off + 12 + xlen:
            si1, si2, slen = data[xoff], data[xoff + 1], struct.unpack_from("<H", data, xoff + 2)[0]
            if si1 == 0x42 and si2 == 0x43:
                bsize = struct.unpack_from("<H", data, xoff + 4)[0] + 1
            xoff += 4 + slen
        if bsize is None:
            raise ValueError(f"{path}: missing BC subfield at offset {off}")
        payload = data[off + 12 + xlen : off + bsize - 8]
        yield off, zlib.decompress(payload, wbits=-15)
        off += bsize


class _RefIndex:
    def __init__(self):
        self.bins: dict[int, list[tuple[int, int]]] = {}
        self.linear: dict[int, int] = {}

    def add(self, beg: int, end: int, v_start: int, v_end: int) -> None:
        b = reg2bin(beg, end)
        chunks = self.bins.setdefault(b, [])
        # merge adjacent chunks (htslib does the same compaction)
        if chunks and chunks[-1][1] >= v_start:
            chunks[-1] = (chunks[-1][0], v_end)
        else:
            chunks.append((v_start, v_end))
        for w in range(beg >> LINEAR_SHIFT, ((max(end, beg + 1) - 1) >> LINEAR_SHIFT) + 1):
            if w not in self.linear or v_start < self.linear[w]:
                self.linear[w] = v_start


def build_tabix_index(
    path: str,
    preset: int = FMT_VCF,
    col_seq: int = 1,
    col_beg: int = 2,
    col_end: int = 0,
    meta_char: str = "#",
) -> str:
    """Build ``<path>.tbi`` for a BGZF VCF/BED; returns the index path.

    Record spans: VCF preset uses POS .. POS+len(REF); BED uses cols 2/3.
    """
    names: list[str] = []
    refs: dict[str, _RefIndex] = {}
    # working buffer + segment map: segments[k] = (buf_index, coff, uoff0)
    # means buf[buf_index:] (until the next segment) lives in the block at
    # compressed offset coff, starting at in-block offset uoff0
    buf = b""
    segments: list[tuple[int, int, int]] = []

    def voffset(i: int) -> int:
        k = len(segments) - 1
        while k > 0 and segments[k][0] > i:
            k -= 1
        buf_index, coff, uoff0 = segments[k]
        return (coff << 16) | (i - buf_index + uoff0)

    for coff, chunk in _iter_bgzf_blocks(path):
        segments.append((len(buf), coff, 0))
        buf += chunk
        pos = 0
        while True:
            nl = buf.find(b"\n", pos)
            if nl < 0:
                break
            _index_line(
                buf[pos:nl], names, refs, voffset(pos), voffset(nl + 1) if nl + 1 < len(buf) else ((coff << 16) | len(chunk)),
                preset, col_seq, col_beg, col_end, meta_char,
            )
            pos = nl + 1
        # drop consumed bytes; rebase surviving segments
        if pos:
            buf = buf[pos:]
            kept = [(bi - pos, c, u) for bi, c, u in segments if bi >= pos]
            # the segment the pointer landed inside survives with shifted uoff
            inside = [(bi, c, u) for bi, c, u in segments if bi < pos]
            if inside:
                bi, c, u = inside[-1]
                kept.insert(0, (0, c, u + (pos - bi)))
            segments = kept
    out = path + ".tbi"
    _write_tbi(out, names, refs, preset, col_seq, col_beg, col_end, meta_char)
    return out


def _index_line(line, names, refs, v_start, v_end, preset, col_seq, col_beg, col_end, meta_char):
    if not line or line.startswith(meta_char.encode()):
        return
    fields = line.split(b"\t")
    try:
        chrom = fields[col_seq - 1].decode()
        beg = int(fields[col_beg - 1])
    except (IndexError, ValueError):
        return
    if preset == FMT_VCF:
        beg -= 1  # VCF is 1-based
        ref_allele = fields[3] if len(fields) > 3 else b"N"
        end = beg + max(len(ref_allele), 1)
    else:
        end = int(fields[col_end - 1]) if col_end and len(fields) >= col_end else beg + 1
    if chrom not in refs:
        names.append(chrom)
        refs[chrom] = _RefIndex()
    refs[chrom].add(beg, end, v_start, v_end)


def _write_tbi(out, names, refs, preset, col_seq, col_beg, col_end, meta_char):
    payload = bytearray()
    payload += TBI_MAGIC
    payload += struct.pack("<i", len(names))
    payload += struct.pack("<6i", preset, col_seq, col_beg, col_end, ord(meta_char), 0)
    nm = b"".join(n.encode() + b"\x00" for n in names)
    payload += struct.pack("<i", len(nm)) + nm
    for name in names:
        ref = refs[name]
        payload += struct.pack("<i", len(ref.bins))
        for b, chunks in sorted(ref.bins.items()):
            payload += struct.pack("<Ii", b, len(chunks))
            for s, e in chunks:
                payload += struct.pack("<QQ", s, e)
        if ref.linear:
            n_intv = max(ref.linear) + 1
            ioff = np.zeros(n_intv, dtype=np.uint64)
            prev = 0
            for w in range(n_intv):
                if w in ref.linear:
                    prev = ref.linear[w]
                ioff[w] = prev
            payload += struct.pack("<i", n_intv) + ioff.tobytes()
        else:
            payload += struct.pack("<i", 0)
    with open(out, "wb") as fh:
        data = bytes(payload)
        for i in range(0, max(len(data), 1), 65280):
            fh.write(compress_block(data[i : i + 65280]))
        from variantcalling_tpu.io.bgzf import BGZF_EOF

        fh.write(BGZF_EOF)


def write_indexed_vcf(path: str, write_fn) -> str:
    """Helper: write a BGZF VCF via ``write_fn(file_like)`` then index it."""
    with BgzfWriter(path) as fh:
        write_fn(fh)
    return build_tabix_index(path)


# ---------------------------------------------------------------- reader ---


def _reg2bins(beg: int, end: int) -> list[int]:
    """All bins overlapping [beg, end) (tabix spec reg2bins)."""
    bins = [0]
    end -= 1
    for shift, base in ((26, 1), (23, 9), (20, 73), (17, 585), (14, 4681)):
        bins.extend(range(base + (beg >> shift), base + (end >> shift) + 1))
    return bins


class TabixIndex:
    """Parsed .tbi: per-contig bins/chunks + linear index, query support."""

    def __init__(self, names, bins, linear, preset, col_seq, col_beg, col_end, meta_char):
        self.names = names
        self.bins = bins  # name -> {bin: [(v_start, v_end)]}
        self.linear = linear  # name -> np.uint64 array
        self.preset = preset
        self.col_seq, self.col_beg, self.col_end = col_seq, col_beg, col_end
        self.meta_char = meta_char

    @staticmethod
    def load(path: str) -> "TabixIndex":
        chunks_data = b"".join(chunk for _, chunk in _iter_bgzf_blocks(path))
        if chunks_data[:4] != TBI_MAGIC:
            raise ValueError(f"{path}: not a TBI index")
        off = 4
        (n_ref,) = struct.unpack_from("<i", chunks_data, off)
        off += 4
        preset, col_seq, col_beg, col_end, meta, _skip = struct.unpack_from("<6i", chunks_data, off)
        off += 24
        (l_nm,) = struct.unpack_from("<i", chunks_data, off)
        off += 4
        names = chunks_data[off : off + l_nm].rstrip(b"\x00").split(b"\x00")
        names = [n.decode() for n in names]
        off += l_nm
        bins: dict[str, dict[int, list[tuple[int, int]]]] = {}
        linear: dict[str, np.ndarray] = {}
        for name in names:
            (n_bin,) = struct.unpack_from("<i", chunks_data, off)
            off += 4
            b: dict[int, list[tuple[int, int]]] = {}
            for _ in range(n_bin):
                bin_id, n_chunk = struct.unpack_from("<Ii", chunks_data, off)
                off += 8
                cs = []
                for _ in range(n_chunk):
                    s, e = struct.unpack_from("<QQ", chunks_data, off)
                    off += 16
                    cs.append((s, e))
                b[bin_id] = cs
            (n_intv,) = struct.unpack_from("<i", chunks_data, off)
            off += 4
            linear[name] = np.frombuffer(chunks_data, dtype=np.uint64, count=n_intv, offset=off).copy()
            off += 8 * n_intv
            bins[name] = b
        return TabixIndex(names, bins, linear, preset, col_seq, col_beg, col_end, chr(meta))

    def query_chunks(self, chrom: str, beg: int, end: int) -> list[tuple[int, int]]:
        """Candidate (v_start, v_end) chunks for 0-based [beg, end)."""
        if chrom not in self.bins:
            return []
        min_off = 0
        lin = self.linear.get(chrom)
        if lin is not None and len(lin) and (beg >> LINEAR_SHIFT) < len(lin):
            min_off = int(lin[beg >> LINEAR_SHIFT])
        out = []
        for b in _reg2bins(beg, end):
            for s, e in self.bins[chrom].get(b, []):
                if e > min_off:
                    out.append((max(s, min_off), e))
        out.sort()
        # merge overlapping chunk ranges so no line is read (or yielded) twice
        merged: list[tuple[int, int]] = []
        for s, e in out:
            if merged and s <= merged[-1][1]:
                merged[-1] = (merged[-1][0], max(merged[-1][1], e))
            else:
                merged.append((s, e))
        return merged


def read_region_lines(vcf_path: str, chrom: str, beg: int, end: int, index: TabixIndex | None = None):
    """Record lines overlapping 0-based [beg, end), via the .tbi index.

    Seeks straight to candidate BGZF blocks (virtual offsets), so a region
    read touches only the blocks that cover it.
    """
    index = index or TabixIndex.load(vcf_path + ".tbi")
    chunks = index.query_chunks(chrom, beg, end)
    if not chunks:
        return
    with open(vcf_path, "rb") as fh:
        data = fh.read()

    def inflate_block(coff: int) -> tuple[bytes, int]:
        xlen = struct.unpack_from("<H", data, coff + 10)[0]
        xoff = coff + 12
        bsize = None
        while xoff < coff + 12 + xlen:
            si1, si2, slen = data[xoff], data[xoff + 1], struct.unpack_from("<H", data, xoff + 2)[0]
            if si1 == 0x42 and si2 == 0x43:
                bsize = struct.unpack_from("<H", data, xoff + 4)[0] + 1
            xoff += 4 + slen
        return zlib.decompress(data[coff + 12 + xlen : coff + bsize - 8], wbits=-15), coff + bsize

    cache: dict[int, tuple[bytes, int]] = {}
    for v_start, v_end in chunks:
        coff, uoff = v_start >> 16, v_start & 0xFFFF
        end_coff, end_uoff = v_end >> 16, v_end & 0xFFFF
        text = bytearray()
        while True:
            if coff not in cache:
                cache[coff] = inflate_block(coff)
            chunk_data, next_coff = cache[coff]
            stop = end_uoff if coff == end_coff else len(chunk_data)
            text += chunk_data[uoff:stop]
            if coff == end_coff or next_coff >= len(data):
                break
            coff, uoff = next_coff, 0
        for line in bytes(text).split(b"\n"):
            if not line or line.startswith(index.meta_char.encode()):
                continue
            fields = line.split(b"\t")
            try:
                c = fields[index.col_seq - 1].decode()
                p = int(fields[index.col_beg - 1])
            except (IndexError, ValueError):
                continue
            if index.preset == FMT_VCF:
                rb = p - 1
                re_ = rb + max(len(fields[3]) if len(fields) > 3 else 1, 1)
            else:
                rb = p
                re_ = int(fields[index.col_end - 1]) if index.col_end else rb + 1
            if c == chrom and rb < end and re_ > beg:
                yield line.decode()
