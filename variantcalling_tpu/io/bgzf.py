"""Pure-Python BGZF codec (blocked gzip, the htslib container framing).

The reference leans on bgzip/tabix binaries for every compressed artifact
(bash/index_vcf_file.sh, compress_gvcf.py:214). Writing plain gzip would
break the drop-in contract — ``tabix``/``bcftools index`` refuse non-BGZF
input — so this framework's writers emit true BGZF blocks: independent
<=64KiB gzip members carrying the BC extra-field with the block size, and
the canonical 28-byte EOF sentinel. Reading BGZF needs nothing special
(it is valid multi-member gzip).
"""

from __future__ import annotations

import struct
import zlib

MAX_BLOCK_DATA = 65280  # uncompressed payload per block (htslib convention)
BGZF_EOF = bytes.fromhex("1f8b08040000000000ff0600424302001b0003000000000000000000")


def compress_block(data: bytes, level: int = 6) -> bytes:
    """One complete BGZF block for <=64KiB of payload."""
    co = zlib.compressobj(level, zlib.DEFLATED, -15)
    deflated = co.compress(data) + co.flush()
    bsize = len(deflated) + 26  # header(18) + deflated + crc/isize(8)
    if bsize - 1 > 0xFFFF:
        raise ValueError("BGZF block overflow (incompressible 64K payload)")
    header = (
        b"\x1f\x8b\x08\x04"  # magic, CM=deflate, FLG=FEXTRA
        + b"\x00\x00\x00\x00"  # MTIME
        + b"\x00\xff"  # XFL, OS=unknown
        + struct.pack("<H", 6)  # XLEN
        + b"BC"
        + struct.pack("<H", 2)
        + struct.pack("<H", bsize - 1)  # spec: BSIZE = total block size - 1
    )
    trailer = struct.pack("<II", zlib.crc32(data) & 0xFFFFFFFF, len(data) & 0xFFFFFFFF)
    return header + deflated + trailer


class BgzfWriter:
    """File-like text/binary writer emitting BGZF blocks."""

    def __init__(self, path: str, level: int = 6):
        self._fh = open(path, "wb")
        self._buf = bytearray()
        self._level = level

    def write(self, data: str | bytes | memoryview) -> int:
        if isinstance(data, str):
            data = data.encode("utf-8")
        n_in = len(data)
        # large-write fast path (the streaming executor hands multi-MB
        # chunk bodies): compress straight from the caller's buffer instead
        # of round-tripping every byte through the bytearray twice
        if not self._buf and n_in >= MAX_BLOCK_DATA:
            view = memoryview(data)
            n_full = (n_in // MAX_BLOCK_DATA) * MAX_BLOCK_DATA
            self._fh.write(self._compress_blocks(bytes(view[:n_full])))
            if n_full < n_in:
                self._buf += view[n_full:]
            return n_in
        self._buf += data
        if len(self._buf) >= MAX_BLOCK_DATA:
            n_full = (len(self._buf) // MAX_BLOCK_DATA) * MAX_BLOCK_DATA
            chunk = bytes(self._buf[:n_full])
            del self._buf[:n_full]
            self._fh.write(self._compress_blocks(chunk))
        return n_in

    def _compress_blocks(self, chunk: bytes) -> bytes:
        """Compress a multiple-of-block-size payload (C path when built)."""
        from variantcalling_tpu import native

        out = native.bgzf_compress(chunk, self._level)
        if out is not None:
            return out[:-28]  # strip the EOF sentinel; close() writes it once
        return b"".join(
            compress_block(chunk[i : i + MAX_BLOCK_DATA], self._level)
            for i in range(0, len(chunk), MAX_BLOCK_DATA)
        )

    def close(self) -> None:
        if self._fh.closed:
            return
        if self._buf:
            self._fh.write(compress_block(bytes(self._buf), self._level))
            self._buf.clear()
        self._fh.write(BGZF_EOF)
        self._fh.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def open_bgzf_text(path: str, level: int = 6) -> BgzfWriter:
    return BgzfWriter(path, level)
