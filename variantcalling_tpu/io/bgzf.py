"""Pure-Python BGZF codec (blocked gzip, the htslib container framing).

The reference leans on bgzip/tabix binaries for every compressed artifact
(bash/index_vcf_file.sh, compress_gvcf.py:214). Writing plain gzip would
break the drop-in contract — ``tabix``/``bcftools index`` refuse non-BGZF
input — so this framework's writers emit true BGZF blocks: independent
<=64KiB gzip members carrying the BC extra-field with the block size, and
the canonical 28-byte EOF sentinel. Reading BGZF needs nothing special
(it is valid multi-member gzip).

BGZF members are INDEPENDENT deflate streams, which is what makes the
parallel host-IO paths possible (docs/streaming_executor.md): the sharded
ingest splits compressed input at member boundaries (:func:`scan_block_spans`)
and inflates shards on a worker pool; the streaming writeback compresses
chunk bodies block-parallel through :class:`BgzfChunkCompressor`, whose
framing is byte-identical to a serial :class:`BgzfWriter` by construction.
"""

from __future__ import annotations

import struct
import zlib

MAX_BLOCK_DATA = 65280  # uncompressed payload per block (htslib convention)
BGZF_EOF = bytes.fromhex("1f8b08040000000000ff0600424302001b0003000000000000000000")


def compress_block(data, level: int = 6) -> bytes:
    """One complete BGZF block for <=64KiB of payload (bytes-like)."""
    co = zlib.compressobj(level, zlib.DEFLATED, -15)
    deflated = co.compress(data) + co.flush()
    bsize = len(deflated) + 26  # header(18) + deflated + crc/isize(8)
    if bsize - 1 > 0xFFFF:
        raise ValueError("BGZF block overflow (incompressible 64K payload)")
    header = (
        b"\x1f\x8b\x08\x04"  # magic, CM=deflate, FLG=FEXTRA
        + b"\x00\x00\x00\x00"  # MTIME
        + b"\x00\xff"  # XFL, OS=unknown
        + struct.pack("<H", 6)  # XLEN
        + b"BC"
        + struct.pack("<H", 2)
        + struct.pack("<H", bsize - 1)  # spec: BSIZE = total block size - 1
    )
    trailer = struct.pack("<II", zlib.crc32(data) & 0xFFFFFFFF, len(data) & 0xFFFFFFFF)
    return header + deflated + trailer


def scan_block_spans(buf) -> list[tuple[int, int, int]] | None:
    """Walk the BGZF member chain of ``buf`` (bytes-like, random access).

    Returns ``[(compressed_offset, compressed_size, uncompressed_size)]``
    per member — the shard map of the parallel ingest — or None when the
    stream is not cleanly BGZF-framed end to end (plain single-member
    gzip, a missing BC subfield, or a truncated chain): callers then use
    the serial gzip path, which handles those exactly as before.
    """
    mv = memoryview(buf)
    n = len(mv)
    spans: list[tuple[int, int, int]] = []
    off = 0
    try:
        while off < n:
            if n - off < 18 or bytes(mv[off:off + 4]) != b"\x1f\x8b\x08\x04":
                return None  # not BGZF-framed (magic/FEXTRA missing)
            (xlen,) = struct.unpack("<H", mv[off + 10:off + 12])
            xoff = off + 12
            xend = xoff + xlen
            if xend > n:
                return None
            bsize = None
            while xoff + 4 <= xend:
                si1, si2 = mv[xoff], mv[xoff + 1]
                (slen,) = struct.unpack("<H", mv[xoff + 2:xoff + 4])
                if si1 == 0x42 and si2 == 0x43 and slen == 2:
                    if xoff + 6 > n:
                        return None  # truncated inside the BC payload
                    (b,) = struct.unpack("<H", mv[xoff + 4:xoff + 6])
                    bsize = b + 1
                xoff += 4 + slen
            if bsize is None or off + bsize > n or bsize < 12 + xlen + 8:
                return None
            (isize,) = struct.unpack("<I", mv[off + bsize - 4:off + bsize])
            spans.append((off, bsize, isize))
            off += bsize
    except struct.error:
        return None  # truncated mid-field: same contract as any bad chain
    return spans


def group_spans(spans, shard_bytes: int) -> list[list[tuple[int, int, int]]]:
    """Group consecutive BGZF member spans into inflate shards of
    ~``shard_bytes`` decompressed bytes — the ONE shard-packing rule,
    shared by the parallel ingest stream and the bench ``io`` phase so
    the microbench always measures the production shard shape."""
    groups: list[list[tuple[int, int, int]]] = []
    cur: list[tuple[int, int, int]] = []
    acc = 0
    for span in spans:
        cur.append(span)
        acc += span[2]
        if acc >= shard_bytes:
            groups.append(cur)
            cur, acc = [], 0
    if cur:
        groups.append(cur)
    return groups


def inflate_spans(buf, spans) -> bytes:
    """Inflate a run of BGZF members of ``buf`` (one ingest shard's work;
    each member is an independent raw-deflate stream). zlib releases the
    GIL, so shards genuinely overlap on the IO worker pool."""
    mv = memoryview(buf)
    out = []
    for off, bsize, _isize in spans:
        (xlen,) = struct.unpack("<H", mv[off + 10:off + 12])
        out.append(zlib.decompress(mv[off + 12 + xlen:off + bsize - 8], wbits=-15))
    return b"".join(out)


def _compress_full_blocks(chunk, level: int, pool=None) -> bytes:
    """BGZF blocks (no EOF sentinel) for a multiple-of-MAX_BLOCK_DATA
    payload — the ONE compressed-framing spelling shared by
    :class:`BgzfWriter` and :class:`BgzfChunkCompressor`, so serial and
    streaming outputs cannot drift. ``chunk`` is bytes-like and is never
    copied here: the native engine deflates straight from the caller's
    buffer (block-sharded internally); without it, blocks deflate on
    ``pool`` when given (the writeback fan-out), inline otherwise.
    """
    from variantcalling_tpu import native

    out = native.bgzf_compress(chunk, level)
    if out is not None:
        return out[:-28]  # strip the EOF sentinel; close()/finish() writes it once
    view = memoryview(chunk)
    blocks = [view[i:i + MAX_BLOCK_DATA] for i in range(0, len(view), MAX_BLOCK_DATA)]
    if pool is not None and len(blocks) > 1:
        from variantcalling_tpu.parallel.pipeline import imap_ordered

        return b"".join(imap_ordered(pool, lambda b: compress_block(b, level),
                                     blocks, window=2 * pool.threads))
    return b"".join(compress_block(b, level) for b in blocks)


class BgzfWriter:
    """File-like text/binary writer emitting BGZF blocks."""

    def __init__(self, path: str, level: int = 6):
        self._fh = open(path, "wb")
        self._buf = bytearray()
        self._level = level

    def write(self, data: str | bytes | memoryview) -> int:
        if isinstance(data, str):
            data = data.encode("utf-8")
        n_in = len(data)
        # large-write fast path (the streaming executor hands multi-MB
        # chunk bodies): compress straight from the caller's buffer —
        # the memoryview rides through to the compressor, so the chunk
        # body is never copied on its way to deflate
        if not self._buf and n_in >= MAX_BLOCK_DATA:
            view = memoryview(data)
            n_full = (n_in // MAX_BLOCK_DATA) * MAX_BLOCK_DATA
            self._fh.write(_compress_full_blocks(view[:n_full], self._level))
            if n_full < n_in:
                self._buf += view[n_full:]
            return n_in
        self._buf += data
        if len(self._buf) >= MAX_BLOCK_DATA:
            n_full = (len(self._buf) // MAX_BLOCK_DATA) * MAX_BLOCK_DATA
            chunk = bytes(self._buf[:n_full])
            del self._buf[:n_full]
            self._fh.write(_compress_full_blocks(chunk, self._level))
        return n_in

    def close(self) -> None:
        if self._fh.closed:
            return
        if self._buf:
            self._fh.write(compress_block(bytes(self._buf), self._level))
            self._buf.clear()
        self._fh.write(BGZF_EOF)
        self._fh.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class BgzfChunkCompressor:
    """Deterministic BGZF framing for the streaming writeback's compress
    stage (docs/streaming_executor.md "Parallel host IO").

    The byte stream is split into consecutive ``MAX_BLOCK_DATA`` payloads
    exactly as a serial :class:`BgzfWriter` would (the carry is always
    ``stream_length mod MAX_BLOCK_DATA``, independent of write sizes), so
    the compressed output is byte-identical to the serial writer
    regardless of chunk boundaries or worker count. :meth:`add` runs on
    ONE pipeline stage thread in chunk order — the carry is therefore
    deterministic — while the deflate work itself fans out (native
    block-sharded compressor, or per-block on ``pool``).
    """

    def __init__(self, level: int = 6, pool=None):
        self._carry = bytearray()
        self._level = level
        self._pool = pool
        self.bytes_in = 0

    def add(self, body) -> bytes:
        """Compressed blocks for every full payload of carry+body; the
        remainder becomes the next carry. ``body`` is bytes-like and is
        not copied when it alone covers the full blocks."""
        from variantcalling_tpu.utils import faults

        # injection point "io.shard_compress": a compress-worker death is
        # a stage exception — the pipeline cancels cleanly and the atomic
        # commit discards the torn .partial (test_streaming_faults)
        faults.check("io.shard_compress")
        view = memoryview(body) if not isinstance(body, memoryview) else body
        self.bytes_in += len(view)
        if not self._carry:
            n_full = (len(view) // MAX_BLOCK_DATA) * MAX_BLOCK_DATA
            out = _compress_full_blocks(view[:n_full], self._level,
                                        self._pool) if n_full else b""
            if n_full < len(view):
                self._carry += view[n_full:]
            return out
        need = MAX_BLOCK_DATA - len(self._carry)
        if len(view) < need:
            self._carry += view
            return b""
        self._carry += view[:need]
        head = bytes(self._carry)
        self._carry.clear()
        rest = view[need:]
        n_full = (len(rest) // MAX_BLOCK_DATA) * MAX_BLOCK_DATA
        out = _compress_full_blocks(head, self._level, self._pool)
        if n_full:
            out += _compress_full_blocks(rest[:n_full], self._level, self._pool)
        if n_full < len(rest):
            self._carry += rest[n_full:]
        return out

    def finish(self) -> bytes:
        """The final partial block (if any) + the EOF sentinel — the same
        tail a serial :class:`BgzfWriter.close` writes."""
        out = b""
        if self._carry:
            out = compress_block(bytes(self._carry), self._level)
            self._carry.clear()
        return out + BGZF_EOF


def open_bgzf_text(path: str, level: int = 6) -> BgzfWriter:
    return BgzfWriter(path, level)
