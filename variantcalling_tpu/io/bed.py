"""BED / Picard interval_list parsing and host-side interval algebra.

Replaces the reference's bedtools/pybedtools subprocess layer
(coverage_analysis.py:732, quick_fingerprinter.py:56-72) with numpy
sorted-interval operations; device-side membership joins live in
:mod:`variantcalling_tpu.ops.intervals`.

Intervals are half-open 0-based [start, end) as in BED.
"""

from __future__ import annotations

import gzip
from dataclasses import dataclass, field

import numpy as np


@dataclass
class IntervalSet:
    """Columnar interval set: parallel arrays (chrom str, start, end)."""

    chrom: np.ndarray  # object (str)
    start: np.ndarray  # int64
    end: np.ndarray  # int64
    name: np.ndarray | None = None
    header_lines: list[str] = field(default_factory=list)

    def __len__(self) -> int:
        return len(self.start)

    def total_length(self) -> int:
        return int(np.sum(self.end - self.start))

    def by_chrom(self) -> dict[str, tuple[np.ndarray, np.ndarray]]:
        """chrom -> (starts, ends), each sorted by start."""
        out: dict[str, tuple[np.ndarray, np.ndarray]] = {}
        for c in dict.fromkeys(self.chrom.tolist()):
            m = self.chrom == c
            s, e = self.start[m], self.end[m]
            order = np.argsort(s, kind="stable")
            out[c] = (s[order], e[order])
        return out

    def merged(self) -> "IntervalSet":
        """Union of overlapping/adjacent intervals (bedtools merge semantics)."""
        chroms: list[str] = []
        starts: list[int] = []
        ends: list[int] = []
        for c, (s, e) in self.by_chrom().items():
            cur_s = cur_e = None
            for i in range(len(s)):
                if cur_s is None:
                    cur_s, cur_e = int(s[i]), int(e[i])
                elif int(s[i]) <= cur_e:
                    cur_e = max(cur_e, int(e[i]))
                else:
                    chroms.append(c)
                    starts.append(cur_s)
                    ends.append(cur_e)
                    cur_s, cur_e = int(s[i]), int(e[i])
            if cur_s is not None:
                chroms.append(c)
                starts.append(cur_s)
                ends.append(cur_e)
        return IntervalSet(_obj(chroms), np.asarray(starts, dtype=np.int64), np.asarray(ends, dtype=np.int64))

    def intersect(self, other: "IntervalSet") -> "IntervalSet":
        """Pairwise intersection (bedtools intersect), via merged sweeps per chrom."""
        a = self.merged().by_chrom()
        b = other.merged().by_chrom()
        chroms: list[str] = []
        starts: list[int] = []
        ends: list[int] = []
        for c in a:
            if c not in b:
                continue
            sa, ea = a[c]
            sb, eb = b[c]
            i = j = 0
            while i < len(sa) and j < len(sb):
                lo = max(sa[i], sb[j])
                hi = min(ea[i], eb[j])
                if lo < hi:
                    chroms.append(c)
                    starts.append(int(lo))
                    ends.append(int(hi))
                if ea[i] < eb[j]:
                    i += 1
                else:
                    j += 1
        return IntervalSet(_obj(chroms), np.asarray(starts, dtype=np.int64), np.asarray(ends, dtype=np.int64))

    def subtract(self, other: "IntervalSet") -> "IntervalSet":
        """Set difference self \\ other (bedtools subtract), merged sweeps per chrom."""
        a = self.merged().by_chrom()
        b = other.merged().by_chrom()
        chroms: list[str] = []
        starts: list[int] = []
        ends: list[int] = []
        for c in a:
            sa, ea = a[c]
            sb, eb = b[c] if c in b else (np.empty(0, dtype=np.int64), np.empty(0, dtype=np.int64))
            j = 0
            for i in range(len(sa)):
                cur = int(sa[i])
                end = int(ea[i])
                while j < len(sb) and eb[j] <= cur:
                    j += 1
                k = j
                while k < len(sb) and sb[k] < end:
                    if cur < sb[k]:
                        chroms.append(c)
                        starts.append(cur)
                        ends.append(int(sb[k]))
                    cur = max(cur, int(eb[k]))
                    k += 1
                if cur < end:
                    chroms.append(c)
                    starts.append(cur)
                    ends.append(end)
        return IntervalSet(_obj(chroms), np.asarray(starts, dtype=np.int64), np.asarray(ends, dtype=np.int64))

    def contains(self, chrom: np.ndarray, pos0: np.ndarray) -> np.ndarray:
        """Membership of 0-based positions; vectorized searchsorted per chrom."""
        out = np.zeros(len(pos0), dtype=bool)
        merged = self.merged().by_chrom()
        chrom = np.asarray(chrom)
        for c, (s, e) in merged.items():
            m = chrom == c
            if not m.any():
                continue
            idx = np.searchsorted(s, pos0[m], side="right") - 1
            ok = idx >= 0
            hit = np.zeros(m.sum(), dtype=bool)
            hit[ok] = pos0[m][ok] < e[idx[ok]]
            out[m] = hit
        return out


def _obj(x: list[str]) -> np.ndarray:
    a = np.empty(len(x), dtype=object)
    a[:] = x
    return a


def _open_text(path: str):
    from variantcalling_tpu.io.vcf import _open_text as _vcf_open_text

    return _vcf_open_text(path)


def read_bed(path: str) -> IntervalSet:
    """Read BED (3+ columns); tolerates track/browser/# headers."""
    chroms: list[str] = []
    starts: list[int] = []
    ends: list[int] = []
    names: list[str] = []
    headers: list[str] = []
    with _open_text(path) as fh:
        for line in fh:
            line = line.rstrip("\n")
            if not line or line.startswith(("#", "track", "browser")):
                headers.append(line)
                continue
            p = line.split("\t")
            chroms.append(p[0])
            starts.append(int(p[1]))
            ends.append(int(p[2]))
            names.append(p[3] if len(p) > 3 else "")
    return IntervalSet(
        _obj(chroms),
        np.asarray(starts, dtype=np.int64),
        np.asarray(ends, dtype=np.int64),
        name=_obj(names),
        header_lines=headers,
    )


def read_interval_list(path: str) -> IntervalSet:
    """Picard .interval_list: SAM-style @ header + 1-based inclusive rows.

    Replaces picard IntervalListToBed (coverage_analysis.py:895).
    """
    chroms: list[str] = []
    starts: list[int] = []
    ends: list[int] = []
    headers: list[str] = []
    with _open_text(path) as fh:
        for line in fh:
            line = line.rstrip("\n")
            if not line or line.startswith("@"):
                headers.append(line)
                continue
            p = line.split("\t")
            chroms.append(p[0])
            starts.append(int(p[1]) - 1)  # 1-based inclusive -> 0-based half-open
            ends.append(int(p[2]))
    return IntervalSet(
        _obj(chroms), np.asarray(starts, dtype=np.int64), np.asarray(ends, dtype=np.int64), header_lines=headers
    )


def read_intervals(path: str) -> IntervalSet:
    """Dispatch on extension: .bed(.gz) or .interval_list (reference IntervalFile behavior)."""
    s = str(path)
    if s.endswith(".interval_list"):
        return read_interval_list(path)
    return read_bed(path)


def write_bed(path: str, intervals: IntervalSet) -> None:
    opener = gzip.open if str(path).endswith(".gz") else open
    with opener(path, "wt") as out:
        for i in range(len(intervals)):
            cols = [str(intervals.chrom[i]), str(int(intervals.start[i])), str(int(intervals.end[i]))]
            if intervals.name is not None and intervals.name[i]:
                cols.append(str(intervals.name[i]))
            out.write("\t".join(cols) + "\n")


class BedWriter:
    """Streaming BED writer (parity: ugbio_core.vcfbed.bed_writer.BedWriter)."""

    def __init__(self, path: str):
        self._path = path
        self._fh = (gzip.open if str(path).endswith(".gz") else open)(path, "wt")

    def write(self, chrom: str, start: int, end: int, *extra) -> None:
        cols = [chrom, str(start), str(end), *map(str, extra)]
        self._fh.write("\t".join(cols) + "\n")

    def close(self) -> None:
        self._fh.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
