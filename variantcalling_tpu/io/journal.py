"""Chunk journal for resumable, atomic streaming writeback.

The streaming filter executor writes its output through a two-file
protocol so an interrupted run (crash, OOM-kill, SIGKILL) never leaves a
partial file at the destination and can RESUME instead of recomputing:

- ``<out>.partial``  — the output bytes as they accumulate; renamed onto
  the destination (``os.replace``, atomic on POSIX) only after the last
  chunk landed. The destination path either holds a previous complete
  file or nothing — never a torn write.
- ``<out>.journal``  — one JSON line per committed chunk (sequence
  number, record/pass counts, body length, CRC32), after a header line
  binding the journal to the exact input file (size + mtime_ns), chunk
  size and output header bytes. Appended and flushed after the chunk's
  bytes are in the partial file, so the journal never claims more than
  the partial file holds (the reverse — partial ahead of journal — is
  healed by truncation on resume).

Resume contract (``pipelines/filter_variants.run_streaming``): chunk
boundaries are a pure function of (input bytes, chunk_bytes), every
per-variant product is row-local, and the journal pins both — so
"skip the journaled chunks, truncate the partial file to the journaled
watermark, continue" reproduces the uninterrupted output byte for byte
(locked by ``tests/unit/test_streaming_faults.py``).

Anything suspicious — signature mismatch, truncated journal line, CRC
mismatch, partial file shorter than the watermark — degrades to a fresh
run; resume is an optimization, never a correctness risk.
"""

from __future__ import annotations

import json
import os
import zlib
from dataclasses import dataclass, field

from variantcalling_tpu import logger

JOURNAL_SUFFIX = ".journal"
PARTIAL_SUFFIX = ".partial"
_VERSION = 1


def fsync_enabled() -> bool:
    """Journal v2 durability knob: fsync partial + journal per chunk."""
    from variantcalling_tpu import knobs

    return knobs.get_bool("VCTPU_JOURNAL_FSYNC")


def partial_path(out_path: str) -> str:
    return str(out_path) + PARTIAL_SUFFIX


def journal_path(out_path: str) -> str:
    return str(out_path) + JOURNAL_SUFFIX


def input_signature(path: str) -> list[int]:
    st = os.stat(path)
    return [int(st.st_size), int(st.st_mtime_ns)]


@dataclass
class ResumeState:
    """What a valid journal + partial file pair lets us skip."""

    chunks: int  # complete chunks already in the partial file
    watermark: int  # byte offset in the partial file after those chunks
    n_records: int
    n_pass: int


@dataclass
class ChunkJournal:
    """Writer/loader for the ``<out>.journal`` sidecar."""

    out_path: str
    _fh: object | None = field(default=None, repr=False)

    # -- writing -----------------------------------------------------------

    def begin(self, meta: dict) -> None:
        """Start a FRESH journal with the run-identity header line."""
        meta = dict(meta, version=_VERSION)
        self._fh = open(journal_path(self.out_path), "w", encoding="utf-8")
        self._fh.write(json.dumps(meta, sort_keys=True) + "\n")
        self._fh.flush()

    def reopen(self) -> None:
        """Append to an existing journal (resume path)."""
        self._fh = open(journal_path(self.out_path), "a", encoding="utf-8")

    def append(self, seq: int, records: int, passed: int, body_len: int,
               crc: int) -> None:
        assert self._fh is not None, "journal not started"
        self._fh.write(json.dumps(
            {"seq": seq, "records": records, "pass": passed,
             "body_len": body_len, "crc": crc}) + "\n")
        self._fh.flush()
        if fsync_enabled():
            # durability knob (VCTPU_JOURNAL_FSYNC): the journal line
            # reaches the platter before the next chunk starts — a power
            # cut can then cost at most the in-flight chunk. Default off:
            # flush ordering alone already survives process death, and
            # per-chunk fsync costs real throughput on the 5M path.
            os.fsync(self._fh.fileno())

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    def finish(self) -> None:
        """Successful completion: the journal has served its purpose."""
        self.close()
        try:
            os.remove(journal_path(self.out_path))
        except OSError:
            pass

    # -- loading -----------------------------------------------------------

    @staticmethod
    def load(out_path: str) -> tuple[dict, list[dict]] | None:
        """(meta, entries) from an existing journal; None when absent or
        unreadable. A truncated/corrupt LAST line (killed mid-append) is
        dropped; corruption earlier than that invalidates the journal."""
        try:
            with open(journal_path(out_path), encoding="utf-8") as fh:
                lines = fh.read().splitlines()
        except OSError:
            return None
        if not lines:
            return None
        try:
            meta = json.loads(lines[0])
        except ValueError:
            return None
        if not isinstance(meta, dict) or meta.get("version") != _VERSION:
            return None
        entries: list[dict] = []
        for i, line in enumerate(lines[1:]):
            try:
                e = json.loads(line)
            except ValueError:
                if i == len(lines) - 2:  # torn tail line: drop it
                    break
                return None
            if not isinstance(e, dict) or e.get("seq") != len(entries):
                return None  # out-of-order / duplicated entries: distrust all
            entries.append(e)
        return meta, entries


def try_resume(out_path: str, meta: dict) -> ResumeState | None:
    """Validate journal + partial file against this run's identity ``meta``
    and prepare the partial file for continuation.

    On success the partial file is TRUNCATED to the journaled watermark
    (healing a torn final chunk) and a :class:`ResumeState` is returned;
    ANY mismatch or malformation returns None (fresh run) — a corrupt
    journal must never be able to crash every subsequent run.
    """
    try:
        return _try_resume(out_path, meta)
    except (KeyError, ValueError, TypeError, OSError):
        # journal parses as JSON but is structurally wrong (missing
        # fields, non-numeric values): suspicious -> fresh run
        logger.info("streaming resume: malformed journal — fresh run")
        return None


def _try_resume(out_path: str, meta: dict) -> ResumeState | None:
    loaded = ChunkJournal.load(out_path)
    if loaded is None:
        return None
    jmeta, entries = loaded
    expect = dict(meta, version=_VERSION)
    if {k: jmeta.get(k) for k in expect} != expect:
        logger.info("streaming resume: journal identity mismatch — fresh run")
        return None
    if not entries:
        return None
    part = partial_path(out_path)
    try:
        size = os.path.getsize(part)
    except OSError:
        return None
    watermark = int(meta["header_len"]) + sum(int(e["body_len"]) for e in entries)
    if size < watermark:
        logger.info("streaming resume: partial file behind the journal — fresh run")
        return None
    from variantcalling_tpu import knobs

    if knobs.get_str("VCTPU_RESUME_VERIFY") == "full":
        # journal v2 opt-in (VCTPU_RESUME_VERIFY=full): re-read and
        # CRC-check EVERY journaled chunk plus the header bytes before
        # trusting the prefix — for operators who suspect the partial
        # file itself (bad disk, concurrent writer) and will pay a full
        # sequential read to know. Any mismatch degrades to a fresh run.
        try:
            with open(part, "rb") as fh:
                head = fh.read(int(meta["header_len"]))
                if zlib.crc32(head) != int(meta["header_crc"]):
                    logger.info("streaming resume: header CRC mismatch "
                                "(full verify) — fresh run")
                    return None
                for e in entries:
                    body = fh.read(int(e["body_len"]))
                    if len(body) != int(e["body_len"]) \
                            or zlib.crc32(body) != int(e["crc"]):
                        logger.info("streaming resume: chunk %d CRC mismatch "
                                    "(full verify) — fresh run",
                                    int(e["seq"]))
                        return None
        except OSError:
            return None
    else:
        # default: spot-verify the LAST journaled chunk's bytes (cheap;
        # whole-prefix verification re-reads everything a resume is
        # meant to skip — VCTPU_RESUME_VERIFY=full opts into that)
        last = entries[-1]
        try:
            with open(part, "rb") as fh:
                fh.seek(watermark - int(last["body_len"]))
                tail = fh.read(int(last["body_len"]))
        except OSError:
            return None
        if zlib.crc32(tail) != int(last["crc"]):
            logger.info("streaming resume: chunk CRC mismatch — fresh run")
            return None
    if size > watermark:  # torn final chunk beyond the journal: heal it
        with open(part, "r+b") as fh:
            fh.truncate(watermark)
    # heal the journal itself too: a SIGKILL mid-append can leave a torn
    # (newline-less) tail line that load() dropped — appending after it
    # would glue valid JSON onto garbage and poison the NEXT resume.
    # Rewriting meta + the validated entries makes reopen()-append safe.
    j = ChunkJournal(out_path)
    j.begin(jmeta)
    for e in entries:
        j.append(int(e["seq"]), int(e["records"]), int(e["pass"]),
                 int(e["body_len"]), int(e["crc"]))
    j.close()
    return ResumeState(
        chunks=len(entries), watermark=watermark,
        n_records=sum(int(e["records"]) for e in entries),
        n_pass=sum(int(e["pass"]) for e in entries),
    )


def discard(out_path: str) -> None:
    """Remove journal + partial file (non-resumable failure, or a fresh
    run superseding stale leftovers)."""
    for p in (journal_path(out_path), partial_path(out_path)):
        try:
            os.remove(p)
        except OSError:
            pass
