"""Chunk journal for resumable, atomic streaming writeback.

The streaming filter executor writes its output through a two-file
protocol so an interrupted run (crash, OOM-kill, SIGKILL) never leaves a
partial file at the destination and can RESUME instead of recomputing:

- ``<out>.partial``  — the output bytes as they accumulate; renamed onto
  the destination (``os.replace``, atomic on POSIX) only after the last
  chunk landed. The destination path either holds a previous complete
  file or nothing — never a torn write.
- ``<out>.journal``  — one JSON line per committed chunk (sequence
  number, record/pass counts, body length, CRC32), after a header line
  binding the journal to the exact input file (size + mtime_ns), chunk
  size and output header bytes. Appended and flushed after the chunk's
  bytes are in the partial file, so the journal never claims more than
  the partial file holds (the reverse — partial ahead of journal — is
  healed by truncation on resume).

Resume contract (``pipelines/filter_variants.run_streaming``): chunk
boundaries are a pure function of (input bytes, chunk_bytes), every
per-variant product is row-local, and the journal pins both — so
"skip the journaled chunks, truncate the partial file to the journaled
watermark, continue" reproduces the uninterrupted output byte for byte
(locked by ``tests/unit/test_streaming_faults.py``).

Anything suspicious — signature mismatch, truncated journal line, CRC
mismatch, partial file shorter than the watermark — degrades to a fresh
run; resume is an optimization, never a correctness risk.

Rank-partitioned scale-out runs (docs/scaleout.md) ride this protocol
PER RANK: each rank's streaming run targets its own segment path
(``<out>.rank{r}of{N}.seg``), so every rank keeps its own journal +
partial pair and a SIGKILLed rank resumes from ITS journal while its
siblings are untouched — the resume identity additionally pins the rank
layout (``config.ranks``), because a journal written by rank r of N
describes r's chunk span only. Completed segments are sealed by a
``.done`` marker (``parallel/rank_plan.py``) the relaunch skip-path and
the rank-sequenced committer both verify.
"""

from __future__ import annotations

import json
import os
import zlib
from dataclasses import dataclass, field

from variantcalling_tpu import logger

JOURNAL_SUFFIX = ".journal"
PARTIAL_SUFFIX = ".partial"
_VERSION = 1


def fsync_enabled() -> bool:
    """Journal v2 durability knob: fsync partial + journal per chunk."""
    from variantcalling_tpu import knobs

    return knobs.get_bool("VCTPU_JOURNAL_FSYNC")


def partial_path(out_path: str, token: str | None = None) -> str:
    """The in-flight output path. ``token`` (``new_partial_token``)
    makes it run-unique — two concurrent runs targeting the same output
    then accumulate INDEPENDENT partials and the atomic ``os.replace``
    commit makes the destination last-complete-writer-wins, where the
    old fixed ``<out>.partial`` let them silently clobber each other's
    bytes mid-write. ``None`` keeps the legacy fixed name (journals
    written before the token field resume through it)."""
    base = str(out_path) + PARTIAL_SUFFIX
    return f"{base}.{token}" if token else base


def open_partial(out_path: str, token: str | None, mode: str = "wb"):
    """Open the in-flight partial for ``out_path`` — the ONE sanctioned
    partial-open (VCT011 run-state ownership): the streaming sink's
    binary handle comes from here, so the ``.partial`` naming scheme has
    exactly one writer-side spelling and a rename of the scheme cannot
    leave a pipeline opening the old name."""
    return open(partial_path(out_path, token), mode)


def remove_partial(out_path: str, token: str | None) -> None:
    """Best-effort removal of the in-flight partial (failure-exit
    cleanup of a non-resumable run) — the sanctioned spelling of the
    unlink, so droppings-removal tracks the naming scheme."""
    try:
        os.remove(partial_path(out_path, token))
    except OSError:
        pass


def commit_partial(out_path: str, token: str | None) -> None:
    """Atomically commit the partial onto its destination. The source
    is a ``.partial`` sibling by construction (the tmp-sibling idiom
    VCT011 requires), so an interrupted commit never exposes a torn
    destination — either the old bytes or the complete new ones."""
    os.replace(partial_path(out_path, token), out_path)


def list_partials(out_path: str) -> list[str]:
    """Every partial next to ``out_path`` — the legacy fixed name plus
    all unique-suffix partials. The ONE spelling of that glob, shared by
    the chaos/load harnesses, the bench cleanup and the test sentinels,
    so a future change to the naming scheme cannot strand a copy."""
    import glob

    base = str(out_path) + PARTIAL_SUFFIX
    found = [base] if os.path.exists(base) else []
    return found + sorted(glob.glob(glob.escape(base) + ".*"))


def new_partial_token() -> str:
    """A fresh run-unique partial suffix. The leading pid is load-
    bearing: :func:`cleanup_stale_partials` only sweeps partials whose
    owning process is DEAD, so a concurrent live run's partial is never
    collected."""
    return f"{os.getpid()}-{os.urandom(4).hex()}"


def _token_pid(token: str) -> int | None:
    head = token.split("-", 1)[0]
    return int(head) if head.isdigit() else None


#: partial tokens with an OPEN sink in THIS process — pid liveness alone
#: cannot distinguish a serve daemon's in-flight request from its own
#: finished-and-failed one (same pid), so the streaming writer claims
#: its token for the sink's lifetime (set add/discard are GIL-atomic)
_ACTIVE_TOKENS: set[str] = set()


def claim_token(token: str) -> None:
    _ACTIVE_TOKENS.add(token)


def release_token(token: str) -> None:
    _ACTIVE_TOKENS.discard(token)


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except OSError:
        return True  # EPERM: alive under another uid
    return True


def token_in_use(token: str) -> bool:
    """Does a RUNNING process/request own this partial? Another live pid
    always counts as in use (conservative: a recycled pid keeps a stale
    file rather than risking a live one); our own pid counts only while
    the token is claimed by an open sink in this process."""
    pid = _token_pid(token)
    if pid is None or not _pid_alive(pid):
        return False
    if pid != os.getpid():
        return True
    return token in _ACTIVE_TOKENS


def cleanup_stale_partials(out_path: str) -> None:
    """Sweep ABANDONED unique-suffix partials next to ``out_path``: any
    ``<out>.partial.<pid>-<hex>`` no running process/request owns
    (:func:`token_in_use` — dead owner pid, or this process's pid with
    no open sink claiming the token). A live FOREIGN pid's partial is
    left strictly alone; unclaimed own-pid orphans must go, or a
    long-lived serve daemon slowly accretes them."""
    import glob

    prefix = str(out_path) + PARTIAL_SUFFIX + "."
    for p in glob.glob(glob.escape(str(out_path) + PARTIAL_SUFFIX) + ".*"):
        token = p[len(prefix):]
        if _token_pid(token) is None:
            continue  # not our naming scheme — leave it
        if token_in_use(token):
            continue
        try:
            os.remove(p)
            logger.info("swept stale partial %s (no live owner)", p)
        except OSError:
            pass


def journal_path(out_path: str) -> str:
    return str(out_path) + JOURNAL_SUFFIX


# the (size, mtime_ns) input pin moved to io/identity.py — the ONE
# spelling shared with the segment markers and the chunk cache; this
# re-export keeps the journal's historical import surface working
from variantcalling_tpu.io.identity import input_signature  # noqa: F401


@dataclass
class ResumeState:
    """What a valid journal + partial file pair lets us skip."""

    chunks: int  # complete chunks already in the partial file
    watermark: int  # byte offset in the partial file after those chunks
    n_records: int
    n_pass: int
    #: unique partial suffix the journal recorded (None: legacy fixed
    #: ``<out>.partial`` written before the token field)
    partial_token: str | None = None


@dataclass
class ChunkJournal:
    """Writer/loader for the ``<out>.journal`` sidecar."""

    out_path: str
    _fh: object | None = field(default=None, repr=False)

    # -- writing -----------------------------------------------------------

    def begin(self, meta: dict) -> None:
        """Start a FRESH journal with the run-identity header line."""
        meta = dict(meta, version=_VERSION)
        self._fh = open(journal_path(self.out_path), "w", encoding="utf-8")
        self._fh.write(json.dumps(meta, sort_keys=True) + "\n")
        self._fh.flush()

    def reopen(self) -> None:
        """Append to an existing journal (resume path)."""
        self._fh = open(journal_path(self.out_path), "a", encoding="utf-8")

    def append(self, seq: int, records: int, passed: int, body_len: int,
               crc: int, in_end: int | None = None) -> None:
        assert self._fh is not None, "journal not started"
        entry = {"seq": seq, "records": records, "pass": passed,
                 "body_len": body_len, "crc": crc}
        if in_end is not None:
            # absolute decompressed END offset of the chunk's INPUT span
            # — the elastic re-cut rule (parallel/elastic.py) splits a
            # dead rank's span at the last journaled in_end, so the
            # journaled prefix is adoptable as a complete sub-span and
            # the remainder re-cuts fresh. Optional: journals without it
            # (older writers) degrade to whole-span re-assignment.
            entry["in_end"] = int(in_end)
        self._fh.write(json.dumps(entry) + "\n")
        self._fh.flush()
        if fsync_enabled():
            # durability knob (VCTPU_JOURNAL_FSYNC): the journal line
            # reaches the platter before the next chunk starts — a power
            # cut can then cost at most the in-flight chunk. Default off:
            # flush ordering alone already survives process death, and
            # per-chunk fsync costs real throughput on the 5M path.
            os.fsync(self._fh.fileno())

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    def finish(self) -> None:
        """Successful completion: the journal has served its purpose."""
        self.close()
        try:
            os.remove(journal_path(self.out_path))
        except OSError:
            pass

    # -- loading -----------------------------------------------------------

    @staticmethod
    def load(out_path: str) -> tuple[dict, list[dict]] | None:
        """(meta, entries) from an existing journal; None when absent or
        unreadable. A truncated/corrupt LAST line (killed mid-append) is
        dropped; corruption earlier than that invalidates the journal."""
        try:
            with open(journal_path(out_path), encoding="utf-8") as fh:
                lines = fh.read().splitlines()
        except OSError:
            return None
        if not lines:
            return None
        try:
            meta = json.loads(lines[0])
        except ValueError:
            return None
        if not isinstance(meta, dict) or meta.get("version") != _VERSION:
            return None
        entries: list[dict] = []
        for i, line in enumerate(lines[1:]):
            try:
                e = json.loads(line)
            except ValueError:
                if i == len(lines) - 2:  # torn tail line: drop it
                    break
                return None
            if not isinstance(e, dict) or e.get("seq") != len(entries):
                return None  # out-of-order / duplicated entries: distrust all
            entries.append(e)
        return meta, entries


def try_resume(out_path: str, meta: dict,
               claim: bool = False) -> ResumeState | None:
    """Validate journal + partial file against this run's identity ``meta``
    and prepare the partial file for continuation.

    On success the partial file is TRUNCATED to the journaled watermark
    (healing a torn final chunk), RE-TOKENED under this process's pid,
    and a :class:`ResumeState` is returned; ANY mismatch or malformation
    returns None (fresh run) — a corrupt journal must never be able to
    crash every subsequent run. ``claim=True`` (the streaming writer)
    additionally claims the new token ATOMICALLY with the rename, so no
    concurrent discard/sweep can take the partial in the gap before the
    writer opens it — the caller then owns :func:`release_token`.
    """
    try:
        return _try_resume(out_path, meta, claim=claim)
    except (KeyError, ValueError, TypeError, OSError):
        # journal parses as JSON but is structurally wrong (missing
        # fields, non-numeric values): suspicious -> fresh run
        logger.info("streaming resume: malformed journal — fresh run")
        return None


def _try_resume(out_path: str, meta: dict,
                claim: bool = False) -> ResumeState | None:
    loaded = ChunkJournal.load(out_path)
    if loaded is None:
        return None
    jmeta, entries = loaded
    expect = dict(meta, version=_VERSION)
    if {k: jmeta.get(k) for k in expect} != expect:
        # say WHICH field invalidated the journal (old vs new value):
        # resume/cache invalidation must be debuggable from production
        # logs, not reproducible-only (io/identity.describe_mismatch)
        from variantcalling_tpu.io import identity as identity_mod

        logger.info("streaming resume: journal identity mismatch (%s) — "
                    "fresh run",
                    identity_mod.describe_mismatch(
                        {k: jmeta.get(k) for k in expect}, expect))
        return None
    if not entries:
        return None
    token = jmeta.get("partial") or None
    if token is not None and token_in_use(token):
        # the journal's partial belongs to a RUNNING process/request —
        # truncating/appending a live writer's file would interleave two
        # runs' bytes. Same-output concurrency is served by the unique
        # partials + atomic commit (last complete writer wins); resume
        # is only for DEAD runs.
        logger.info("streaming resume: the journal's partial is owned by "
                    "a running process — fresh run")
        return None
    part = partial_path(out_path, token)
    try:
        size = os.path.getsize(part)
    except OSError:
        return None
    watermark = int(meta["header_len"]) + sum(int(e["body_len"]) for e in entries)
    if size < watermark:
        logger.info("streaming resume: partial file behind the journal — fresh run")
        return None
    from variantcalling_tpu import knobs

    if knobs.get_str("VCTPU_RESUME_VERIFY") == "full":
        # journal v2 opt-in (VCTPU_RESUME_VERIFY=full): re-read and
        # CRC-check EVERY journaled chunk plus the header bytes before
        # trusting the prefix — for operators who suspect the partial
        # file itself (bad disk, concurrent writer) and will pay a full
        # sequential read to know. Any mismatch degrades to a fresh run.
        try:
            with open(part, "rb") as fh:
                head = fh.read(int(meta["header_len"]))
                if zlib.crc32(head) != int(meta["header_crc"]):
                    logger.info("streaming resume: header CRC mismatch "
                                "(full verify) — fresh run")
                    return None
                for e in entries:
                    body = fh.read(int(e["body_len"]))
                    if len(body) != int(e["body_len"]) \
                            or zlib.crc32(body) != int(e["crc"]):
                        logger.info("streaming resume: chunk %d CRC mismatch "
                                    "(full verify) — fresh run",
                                    int(e["seq"]))
                        return None
        except OSError:
            return None
    else:
        # default: spot-verify the LAST journaled chunk's bytes (cheap;
        # whole-prefix verification re-reads everything a resume is
        # meant to skip — VCTPU_RESUME_VERIFY=full opts into that)
        last = entries[-1]
        try:
            with open(part, "rb") as fh:
                fh.seek(watermark - int(last["body_len"]))
                tail = fh.read(int(last["body_len"]))
        except OSError:
            return None
        if zlib.crc32(tail) != int(last["crc"]):
            logger.info("streaming resume: chunk CRC mismatch — fresh run")
            return None
    if size > watermark:  # torn final chunk beyond the journal: heal it
        with open(part, "r+b") as fh:
            fh.truncate(watermark)
    # RE-TOKEN on resume: the resumed run must own its partial under ITS
    # pid — keeping the dead run's token would let a concurrent fresh
    # run's stale-partial sweep (dead owner pid) delete the file out
    # from under the live resumer. Legacy fixed-name partials adopt the
    # token scheme here the same way.
    new_token = new_partial_token()
    if claim:
        claim_token(new_token)  # before the file exists: no sweep gap
    try:
        os.rename(part, partial_path(out_path, new_token))
        # heal the journal itself too: a SIGKILL mid-append can leave a
        # torn (newline-less) tail line that load() dropped — appending
        # after it would glue valid JSON onto garbage and poison the
        # NEXT resume. Rewriting meta (with the NEW partial token) +
        # the validated entries makes reopen()-append safe.
        j = ChunkJournal(out_path)
        j.begin(dict(jmeta, partial=new_token))
        for e in entries:
            j.append(int(e["seq"]), int(e["records"]), int(e["pass"]),
                     int(e["body_len"]), int(e["crc"]),
                     in_end=e.get("in_end"))
        j.close()
    except BaseException:
        if claim:
            release_token(new_token)  # a failed resume owns nothing
        raise
    return ResumeState(
        chunks=len(entries), watermark=watermark,
        n_records=sum(int(e["records"]) for e in entries),
        n_pass=sum(int(e["pass"]) for e in entries),
        partial_token=new_token,
    )


def discard(out_path: str) -> None:
    """Remove journal + its partial file (non-resumable failure, or a
    fresh run superseding stale leftovers), then sweep abandoned
    partials of dead runs. The journal is read FIRST so the unique-
    suffix partial it names is removed with it — but ONLY when no
    running process/request owns that partial (:func:`token_in_use`): a
    concurrent live run to the same output keeps its data plane intact
    and commits last-complete-writer-wins (its journal/resume
    bookkeeping IS superseded — two journals cannot share one path;
    bytes are safe, a later resume of the loser degrades to fresh)."""
    loaded = ChunkJournal.load(out_path)
    token = loaded[0].get("partial") if loaded else None
    paths = [journal_path(out_path), partial_path(out_path)]
    if token and not token_in_use(token):
        paths.append(partial_path(out_path, token))
    for p in paths:
        try:
            os.remove(p)
        except OSError:
            pass
    cleanup_stale_partials(out_path)
