"""CRAM ingest: native CRAM 3.0 decode -> per-contig depth vectors.

The reference consumes CRAM everywhere via samtools subprocesses
(quick_fingerprinter.py:104-108; BASELINE config 4 is "30x WGS CRAM");
this module serves the same inputs through the in-process C++ decoder
(native/src/vctpu_cram.cc): alignment records come back as flat arrays
(ref_id, 1-based pos, reference span, mapq, flags, read length) and depth
accumulation is one vectorized difference-array pass — no per-record
Python, same downstream device reductions as the BAM path.

Base-level pileup (fingerprinting) is served by the decoder's
reconstruction path: native.cram_pileup rebuilds aligned bases from the
reference + SM substitution matrix (comparison/pileup_caller).

Limitations (explicit, raised or logged — never silent): CRAM 3.1 codecs
and bzip2/lzma blocks are unsupported; per-base-quality depth filtering
(-q) is not applied to CRAM inputs; N (reference-skip) ops count toward
the depth span (DNA pipelines — this framework's domain — do not emit N
ops).
"""

from __future__ import annotations

import numpy as np

from variantcalling_tpu import logger, native
from variantcalling_tpu.io.bam import EXCLUDE_FLAGS, BamHeader


def read_cram_header(path: str) -> BamHeader:
    with open(path, "rb") as fh:
        buf = fh.read()
    return header_from_buffer(buf, path)


def header_from_buffer(buf, path: str = "<buffer>") -> BamHeader:
    text = native.cram_header(buf)
    if text is None:
        raise ValueError(
            f"cannot decode CRAM header of {path}: native engine unavailable or "
            "unsupported CRAM version/codec (supported: CRAM 3.0, raw/gzip/rANS-4x8)"
        )
    refs: list[str] = []
    lengths: dict[str, int] = {}
    for line in text.splitlines():
        if line.startswith("@SQ"):
            name, ln = None, None
            for field in line.split("\t")[1:]:
                if field.startswith("SN:"):
                    name = field[3:]
                elif field.startswith("LN:"):
                    ln = int(field[3:])
            if name is not None and ln is not None:
                refs.append(name)
                lengths[name] = ln
    return BamHeader(text=text, references=refs, lengths=lengths)


def cram_records(path: str) -> tuple[BamHeader, dict]:
    """(header, record arrays) for a whole CRAM file (single read, exact alloc)."""
    with open(path, "rb") as fh:
        buf = fh.read()
    header = header_from_buffer(buf, path)
    n = native.cram_count(buf)
    if n is None:
        raise ValueError(f"cannot walk CRAM containers of {path} (malformed stream?)")
    recs = native.cram_scan(buf, max(n, 1))
    if recs is None or recs == "grow":
        raise ValueError(
            f"cannot decode CRAM records of {path}: unsupported codec or "
            "malformed stream (supported: CRAM 3.0, raw/gzip/rANS-4x8 blocks)"
        )
    return header, recs


def depth_diff_arrays(
    path: str,
    min_bq: int = 0,
    min_mapq: int = 0,
    min_read_length: int = 0,
    include_deletions: bool = True,
    regions: list[str] | None = None,
) -> tuple[BamHeader, dict[str, np.ndarray]]:
    """CRAM counterpart of io.bam.depth_diff_arrays (same contract).

    ``include_deletions`` matches -J semantics at the span level: the CRAM
    record span already covers D/N ops; without -J per-op splitting would
    need feature-level spans (the decoder folds them into one span), so the
    flag only logs when it would differ.
    """
    if min_bq > 0:
        logger.warning("CRAM depth: per-base-quality filter (-q %d) not applied to CRAM inputs",
                       min_bq)
    if not include_deletions:
        logger.warning("CRAM depth: spans include deletions (samtools depth -J semantics)")
    header, recs = cram_records(path)
    region_contigs = {r.split(":")[0] for r in regions} if regions else None

    keep = (recs["flags"] & EXCLUDE_FLAGS) == 0
    keep &= recs["ref_id"] >= 0
    keep &= recs["mapq"] >= min_mapq
    keep &= recs["read_len"] >= min_read_length
    ref_id = recs["ref_id"][keep]
    start0 = recs["pos"][keep] - 1  # CRAM positions are 1-based
    span = np.maximum(recs["span"][keep], 0)

    diffs: dict[str, np.ndarray] = {}
    for rid, name in enumerate(header.references):
        if region_contigs is not None and name not in region_contigs:
            continue
        m = ref_id == rid
        diff = np.zeros(header.lengths[name] + 1, dtype=np.int32)
        if m.any():
            s = np.clip(start0[m], 0, len(diff) - 1)
            e = np.clip(start0[m] + span[m], 0, len(diff) - 1)
            np.add.at(diff, s, 1)
            np.add.at(diff, e, -1)
        diffs[name] = diff
    return header, diffs
