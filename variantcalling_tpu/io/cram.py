"""CRAM ingest: native CRAM 3.0 decode -> per-contig depth vectors.

The reference consumes CRAM everywhere via samtools subprocesses
(quick_fingerprinter.py:104-108; BASELINE config 4 is "30x WGS CRAM");
this module serves the same inputs through the in-process C++ decoder
(native/src/vctpu_cram.cc): alignment records come back as flat arrays
(ref_id, 1-based pos, reference span, mapq, flags, read length) and depth
accumulation is one vectorized difference-array pass — no per-record
Python, same downstream device reductions as the BAM path.

Base-level pileup (fingerprinting) is served by the decoder's
reconstruction path: native.cram_pileup rebuilds aligned bases from the
reference + SM substitution matrix (comparison/pileup_caller).

Depth runs feature-aware in the decoder (native.cram_depth): per-base
quality filtering (``-q``) applies to aligned read bases from the
record's quality array (missing qualities pass, as samtools treats '*'),
deletions cover iff ``-J``, and N (reference-skip) ops never cover —
full samtools-depth parity with the BAM walker.

Limitations (explicit, raised or logged — never silent): CRAM 3.1 codecs
(rANS-Nx16, adaptive arithmetic, fqzcomp, name tokenizer) and bzip2/lzma
blocks are unsupported — decoding raises with a clear message. The 3.1
codecs are deliberately deferred, not forgotten: in this zero-egress
environment a from-memory rANS-Nx16 implementation could only ever be
validated against a same-author encoder, the exact correlated-risk
failure mode the hand-transcribed interop fixtures
(tests/unit/test_interop_fixtures.py) exist to eliminate; htslib's
default write format remains CRAM 3.0, which this decoder covers in
full (including the ``-q`` per-base-quality depth semantics).
"""

from __future__ import annotations

import numpy as np

from variantcalling_tpu import native
from variantcalling_tpu.io.bam import EXCLUDE_FLAGS, BamHeader


def read_cram_header(path: str) -> BamHeader:
    with open(path, "rb") as fh:
        buf = fh.read()
    return header_from_buffer(buf, path)


def header_from_buffer(buf, path: str = "<buffer>") -> BamHeader:
    text = native.cram_header(buf)
    if text is None:
        raise ValueError(
            f"cannot decode CRAM header of {path}: native engine unavailable or "
            "unsupported CRAM version/codec (supported: CRAM 3.0, raw/gzip/rANS-4x8)"
        )
    refs: list[str] = []
    lengths: dict[str, int] = {}
    for line in text.splitlines():
        if line.startswith("@SQ"):
            name, ln = None, None
            for field in line.split("\t")[1:]:
                if field.startswith("SN:"):
                    name = field[3:]
                elif field.startswith("LN:"):
                    ln = int(field[3:])
            if name is not None and ln is not None:
                refs.append(name)
                lengths[name] = ln
    return BamHeader(text=text, references=refs, lengths=lengths)


def cram_records(path: str) -> tuple[BamHeader, dict]:
    """(header, record arrays) for a whole CRAM file (single read, exact alloc)."""
    with open(path, "rb") as fh:
        buf = fh.read()
    header = header_from_buffer(buf, path)
    n = native.cram_count(buf)
    if n is None:
        raise ValueError(f"cannot walk CRAM containers of {path} (malformed stream?)")
    recs = native.cram_scan(buf, max(n, 1))
    if recs is None or recs == "grow":
        raise ValueError(
            f"cannot decode CRAM records of {path}: unsupported codec or "
            "malformed stream (supported: CRAM 3.0, raw/gzip/rANS-4x8 blocks)"
        )
    return header, recs


def depth_diff_arrays(
    path: str,
    min_bq: int = 0,
    min_mapq: int = 0,
    min_read_length: int = 0,
    include_deletions: bool = True,
    regions: list[str] | None = None,
) -> tuple[BamHeader, dict[str, np.ndarray]]:
    """CRAM counterpart of io.bam.depth_diff_arrays (same contract,
    including the per-base ``-q`` filter — the decoder walks alignment
    features with the record's quality array, so CRAM and BAM depth agree
    on mixed-quality data)."""
    with open(path, "rb") as fh:
        buf = fh.read()
    header = header_from_buffer(buf, path)
    region_contigs = {r.split(":")[0] for r in regions} if regions else None

    starts = np.full(len(header.references), -1, dtype=np.int64)
    lens = np.zeros(len(header.references), dtype=np.int64)
    off = 0
    for rid, name in enumerate(header.references):
        if region_contigs is not None and name not in region_contigs:
            continue
        starts[rid] = off
        lens[rid] = header.lengths[name]
        off += header.lengths[name] + 1
    diff_flat = np.zeros(max(off, 1), dtype=np.int32)
    n = native.cram_depth(
        buf, starts, lens, diff_flat,
        min_bq=min_bq, min_mapq=min_mapq, min_read_length=min_read_length,
        include_deletions=include_deletions, exclude_flags=EXCLUDE_FLAGS,
    )
    if n is None or n < 0:
        raise ValueError(
            f"cannot decode CRAM records of {path}: unsupported codec or "
            "malformed stream (supported: CRAM 3.0, raw/gzip/rANS blocks)"
        )
    diffs: dict[str, np.ndarray] = {}
    for rid, name in enumerate(header.references):
        if starts[rid] < 0:
            continue
        diffs[name] = diff_flat[starts[rid] : starts[rid] + header.lengths[name] + 1]
    return header, diffs
