"""Indexed FASTA reader (pyfaidx/pysam.FastaFile equivalent, no native deps).

Supports .fai index files (created on demand for uncompressed FASTA).
Used by featurization for motif windows and hmer detection
(parity targets: calibrate_bridging_snvs.py:3 FastaFile usage,
collect_hpol_table.py pyfaidx usage).

Genome-scale cost structure (the filter pipeline's warmup cliff, VERDICT
round-5 item 4): building the .fai and 2-bit-class-encoding the contigs
used to be serial Python — ~9s of .fai line loop plus ~2s of encode at
250 Mbp, growing linearly to ~1 min at hg38 scale. Both are now
vectorized/threaded, and the encoded genome persists in a sidecar cache
keyed on (path, mtime, size) so repeat runs skip the encode entirely
(memory-mapped load instead).
"""

from __future__ import annotations

import json
import os
import threading
from dataclasses import dataclass

import numpy as np

from variantcalling_tpu import knobs, logger


@dataclass
class _FaiEntry:
    length: int
    offset: int
    line_bases: int
    line_width: int


_FAI_SCAN_CHUNK = 64 << 20


def build_fai(path: str) -> dict[str, _FaiEntry]:
    """Scan a FASTA and build the .fai table (writes <path>.fai).

    Vectorized: newline offsets come from chunked numpy scans over a
    memory map (a 3.1 Gbp genome indexes in seconds; the per-line Python
    loop this replaces took ~1 minute there and was the largest single
    slice of the filter pipeline's warmup).
    """
    entries: dict[str, _FaiEntry] = {}
    order: list[str] = []
    size = os.path.getsize(path)
    if size == 0:
        return entries
    mm = np.memmap(path, dtype=np.uint8, mode="r")
    nl_parts = [
        np.flatnonzero(mm[lo: min(lo + _FAI_SCAN_CHUNK, size)] == 0x0A) + lo
        for lo in range(0, size, _FAI_SCAN_CHUNK)
    ]
    nls = np.concatenate(nl_parts) if nl_parts else np.empty(0, np.int64)
    # line i occupies [starts[i], ends[i]) content plus its newline (if any)
    starts = np.concatenate([[0], nls + 1])
    ends = np.concatenate([nls, [size]])
    if starts[-1] >= size:  # file ends with a newline: no phantom last line
        starts, ends = starts[:-1], ends[:-1]
    # strip \r of CRLF files from the content length
    has_cr = np.zeros(len(starts), dtype=bool)
    inner = ends > starts
    has_cr[inner] = mm[ends[inner] - 1] == 0x0D
    content_len = ends - starts - has_cr
    is_hdr = (mm[starts] == ord(">")) & (ends > starts)
    hdr_lines = np.flatnonzero(is_hdr)
    cum = np.concatenate([[0], np.cumsum(content_len)])
    for k, li in enumerate(hdr_lines):
        name = bytes(mm[starts[li] + 1: ends[li] - has_cr[li]]).split()[0].decode()
        order.append(name)
        body_lo = li + 1
        body_hi = int(hdr_lines[k + 1]) if k + 1 < len(hdr_lines) else len(starts)
        length = int(cum[body_hi] - cum[body_lo])
        line_bases = line_width = 0
        for bi in range(body_lo, body_hi):  # first non-empty body line only
            if content_len[bi] > 0:
                line_bases = int(content_len[bi])
                line_width = int(
                    (starts[bi + 1] if bi + 1 < len(starts) else size) - starts[bi]
                )
                break
        entries[name] = _FaiEntry(length, int(starts[body_lo]) if body_lo < len(starts)
                                  else size, line_bases, line_width)
    del mm
    try:  # cache the index beside the FASTA; read-only mounts just skip it
        with open(path + ".fai", "wt") as out:
            for n in order:
                e = entries[n]
                out.write(f"{n}\t{e.length}\t{e.offset}\t{e.line_bases}\t{e.line_width}\n")
    except OSError as e:
        logger.debug("not caching .fai beside %s: %s", path, e)
    return entries


def read_fai(path: str) -> dict[str, _FaiEntry]:
    entries: dict[str, _FaiEntry] = {}
    with open(path, "rt") as fh:
        for line in fh:
            p = line.rstrip("\n").split("\t")
            entries[p[0]] = _FaiEntry(int(p[1]), int(p[2]), int(p[3]), int(p[4]))
    return entries


#: persistent encoded-genome cache format version (sidecar `<fasta>.venc`)
_VENC_MAGIC = b"VCENC1\n"


class FastaReader:
    """Random-access FASTA with 0-based half-open ``fetch``."""

    def __init__(self, path: str):
        self.path = path
        fai = path + ".fai"
        if os.path.exists(fai):
            self._index = read_fai(fai)
        else:
            self._index = build_fai(path)
        self._fh = open(path, "rb")
        self._encoded: dict[str, np.ndarray] = {}
        self._enc_lock = threading.Lock()
        self._enc_inflight: dict[str, threading.Event] = {}
        self._venc: np.memmap | None = None
        self._venc_offsets: dict[str, tuple[int, int]] = {}
        self._load_persistent_cache()

    @property
    def _ENC_CACHE_BYTES(self) -> int:
        """Byte budget for the encoded-contig cache (default 4 GB covers
        a whole human genome; VCTPU_FASTA_CACHE_BYTES tunes it down for
        memory-constrained workers — 0 disables caching entirely).
        Resolved lazily so a malformed value surfaces as a validated
        configuration error, never an import-time traceback."""
        return knobs.get_int("VCTPU_FASTA_CACHE_BYTES")

    # -- persistent encoded-genome cache ----------------------------------

    def _cache_key(self) -> dict:
        st = os.stat(self.path)
        return {"path": os.path.abspath(self.path),
                "mtime_ns": st.st_mtime_ns, "size": st.st_size}

    def _venc_path(self) -> str:
        d = knobs.get_str("VCTPU_GENOME_CACHE_DIR")
        if d:
            import hashlib

            tag = hashlib.sha256(os.path.abspath(self.path).encode()).hexdigest()[:16]
            return os.path.join(d, f"{os.path.basename(self.path)}.{tag}.venc")
        return self.path + ".venc"

    def _load_persistent_cache(self) -> None:
        """Attach the sidecar encoded-genome cache when its key matches.

        The cache is keyed on (path, mtime, size): a rewritten FASTA
        invalidates it automatically. Loads are memory maps, so a cache
        hit costs no decode and no up-front RSS — repeat pipeline runs
        skip the encode entirely.
        """
        if not knobs.get_bool("VCTPU_GENOME_CACHE"):
            return
        p = self._venc_path()
        try:
            if not os.path.exists(p):
                return
            with open(p, "rb") as fh:
                if fh.read(len(_VENC_MAGIC)) != _VENC_MAGIC:
                    return
                header = json.loads(fh.readline().decode())
                data_off = fh.tell()
            key = self._cache_key()
            if header.get("key", {}).get("mtime_ns") != key["mtime_ns"] or \
                    header.get("key", {}).get("size") != key["size"]:
                return
            mm = np.memmap(p, dtype=np.uint8, mode="r", offset=data_off)
            offsets = {}
            ok = True
            for name, off, length in header.get("contigs", []):
                ent = self._index.get(name)
                if ent is None or ent.length != length or off + length > len(mm):
                    ok = False
                    break
                offsets[name] = (int(off), int(length))
            if ok and len(offsets) == len(self._index):
                self._venc = mm
                self._venc_offsets = offsets
        except (OSError, ValueError, json.JSONDecodeError) as e:
            logger.warning("ignoring unreadable genome cache %s: %s", p, e)
            return

    def _persist_encoded(self) -> bool:
        """Write the sidecar cache from fully in-memory encoded contigs.

        Atomic (tmp + replace); any failure (read-only mount, no space)
        is silently skipped — the cache is an accelerator, not a
        dependency.
        """
        if not knobs.get_bool("VCTPU_GENOME_CACHE") or self._venc is not None:
            return False
        with self._enc_lock:
            have_all = all(c in self._encoded for c in self._index)
            arrays = dict(self._encoded) if have_all else None
        if not have_all:
            return False
        contigs = []
        off = 0
        for name in self._index:
            contigs.append((name, off, int(self._index[name].length)))
            off += int(self._index[name].length)
        header = json.dumps({"key": self._cache_key(), "contigs": contigs}).encode()
        p = self._venc_path()
        tmp = f"{p}.{os.getpid()}.tmp"
        try:
            os.makedirs(os.path.dirname(p) or ".", exist_ok=True)
            with open(tmp, "wb") as fh:
                fh.write(_VENC_MAGIC + header + b"\n")
                for name in self._index:
                    fh.write(memoryview(np.ascontiguousarray(arrays[name])))
            os.replace(tmp, p)
            return True
        except OSError as e:
            logger.warning("could not persist genome cache %s: %s", p, e)
            try:
                if os.path.exists(tmp):
                    os.remove(tmp)
            except OSError:
                pass
            return False

    # -- encoded-contig access --------------------------------------------

    def fetch_encoded(self, chrom: str) -> np.ndarray:
        """Whole-contig uint8 codes (A0 C1 G2 T3 N4), cached per contig —
        repeated window gathers re-read one array instead of re-decoding
        the FASTA text each time. The in-memory cache is byte-bounded
        (FIFO); a valid sidecar ``.venc`` cache serves memory-mapped
        slices instead (no decode at all). Thread-safe: a prefetch thread
        and a scoring thread asking for the same contig encode it once.
        """
        if self._venc is not None:
            span = self._venc_offsets.get(chrom)
            if span is not None:
                return self._venc[span[0]: span[0] + span[1]]
        while True:
            with self._enc_lock:
                got = self._encoded.get(chrom)
                if got is not None:
                    return got
                ev = self._enc_inflight.get(chrom)
                if ev is None:
                    ev = self._enc_inflight[chrom] = threading.Event()
                    break  # this thread encodes
            ev.wait()
        try:
            got = self._encode_contig(chrom)
            with self._enc_lock:
                if len(got) <= self._ENC_CACHE_BYTES:
                    total = sum(len(v) for v in self._encoded.values()) + len(got)
                    while self._encoded and total > self._ENC_CACHE_BYTES:
                        total -= len(self._encoded.pop(next(iter(self._encoded))))
                    self._encoded[chrom] = got
            return got
        finally:
            with self._enc_lock:
                self._enc_inflight.pop(chrom, None).set()

    def encode_all(self, persist: bool = True, cancel=None) -> None:
        """Encode every contig (native threaded path) and, by default,
        persist the sidecar ``.venc`` cache so later processes skip the
        encode. The filter pipeline's streaming executor runs this on a
        prefetch thread so the encode hides behind scoring instead of
        serializing in front of it; ``cancel`` (a threading.Event) lets
        that caller stop between contigs once its own work is done — a
        tiny job on a huge genome must not block on encoding contigs it
        never touched. Persist is skipped when cancelled (a partial cache
        is never written)."""
        if self._venc is not None:
            return
        if sum(e.length for e in self._index.values()) > self._ENC_CACHE_BYTES:
            # the genome can't be held resident: prefetching would FIFO-evict
            # every contig it encodes (wasted CPU competing with scoring) and
            # persist could never see them all — let scoring encode on demand
            return
        for chrom in self._index:
            if cancel is not None and cancel.is_set():
                return
            self.fetch_encoded(chrom)
        if persist and not (cancel is not None and cancel.is_set()):
            self._persist_encoded()

    def _encode_contig(self, chrom: str) -> np.ndarray:
        """Whole-contig encode without the str round-trip: raw bytes ->
        newline strip + one table lookup, threaded in the native engine
        (numpy reshape fallback below it, byte-identical). This is the
        flagship pipeline's first-touch cost per contig; see encode_all /
        the .venc cache for how repeat runs skip it."""
        e = self._index[chrom]
        if e.length == 0:
            return np.empty(0, dtype=np.uint8)
        last_line = (e.length - 1) // e.line_bases
        byte_end = e.offset + last_line * e.line_width + ((e.length - 1) - last_line * e.line_bases) + 1
        with self._enc_lock:  # the shared file handle needs seek+read atomic
            self._fh.seek(e.offset)
            rawb = self._fh.read(byte_end - e.offset)
        raw = np.frombuffer(rawb, dtype=np.uint8)
        gap = e.line_width - e.line_bases  # newline bytes per full line
        if gap == 0:
            return _CODE[raw[: e.length]]
        from variantcalling_tpu import native

        enc = native.fasta_encode(raw, e.line_bases, e.line_width, e.length)
        if enc is not None:
            return enc
        full = len(raw) // e.line_width
        body = _CODE[raw[: full * e.line_width].reshape(full, e.line_width)[:, : e.line_bases]]
        tail = raw[full * e.line_width :]
        if len(tail) == 0:
            return body.reshape(-1)[: e.length]
        return np.concatenate([body.reshape(-1), _CODE[tail[: e.line_bases]]])[: e.length]

    @property
    def references(self) -> list[str]:
        return list(self._index)

    def get_reference_length(self, chrom: str) -> int:
        return self._index[chrom].length

    def fetch(self, chrom: str, start: int, end: int) -> str:
        """Uppercased sequence [start, end), clamped to contig bounds."""
        e = self._index[chrom]
        start = max(0, int(start))
        end = min(e.length, int(end))
        if end <= start:
            return ""
        first_line = start // e.line_bases
        byte_start = e.offset + first_line * e.line_width + (start - first_line * e.line_bases)
        last_line = (end - 1) // e.line_bases
        byte_end = e.offset + last_line * e.line_width + ((end - 1) - last_line * e.line_bases) + 1
        with self._enc_lock:
            self._fh.seek(byte_start)
            data = self._fh.read(byte_end - byte_start)
        return data.replace(b"\n", b"").replace(b"\r", b"").decode().upper()

    def fetch_array(self, chrom: str, start: int, end: int, pad: str = "N") -> np.ndarray:
        """uint8 sequence codes over [start, end) with out-of-bounds padding.

        Codes: A=0 C=1 G=2 T=3 other=4 — the device-side encoding used by the
        featurization kernels.
        """
        seq = self.fetch(chrom, start, end)
        left_pad = max(0, -start)
        right_pad = (end - start) - left_pad - len(seq)
        return encode_seq(pad * left_pad + seq + pad * right_pad)

    def close(self) -> None:
        self._fh.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


_CODE = np.full(256, 4, dtype=np.uint8)
for _i, _b in enumerate(b"ACGT"):
    _CODE[_b] = _i
for _i, _b in enumerate(b"acgt"):
    _CODE[_b] = _i


def encode_seq(seq: str) -> np.ndarray:
    """str -> uint8 codes (A0 C1 G2 T3 N/other 4)."""
    return _CODE[np.frombuffer(seq.encode(), dtype=np.uint8)]


def decode_seq(codes: np.ndarray) -> str:
    return "".join("ACGTN"[c] for c in codes)


def revcomp(seq: str) -> str:
    """Reverse complement (parity: ugbio_core.dna_sequence_utils.revcomp)."""
    comp = {"A": "T", "C": "G", "G": "C", "T": "A", "N": "N", "a": "t", "c": "g", "g": "c", "t": "a"}
    return "".join(comp.get(c, "N") for c in reversed(seq))
