"""Indexed FASTA reader (pyfaidx/pysam.FastaFile equivalent, no native deps).

Supports .fai index files (created on demand for uncompressed FASTA).
Used by featurization for motif windows and hmer detection
(parity targets: calibrate_bridging_snvs.py:3 FastaFile usage,
collect_hpol_table.py pyfaidx usage).
"""

from __future__ import annotations

import os
from dataclasses import dataclass

import numpy as np


@dataclass
class _FaiEntry:
    length: int
    offset: int
    line_bases: int
    line_width: int


def build_fai(path: str) -> dict[str, _FaiEntry]:
    """Scan a FASTA and build the .fai table (writes <path>.fai)."""
    entries: dict[str, _FaiEntry] = {}
    order: list[str] = []
    with open(path, "rb") as fh:
        name = None
        length = 0
        offset = 0
        line_bases = 0
        line_width = 0
        pos = 0
        for raw in fh:
            line_len = len(raw)
            line = raw.rstrip(b"\r\n")
            if line.startswith(b">"):
                if name is not None:
                    entries[name] = _FaiEntry(length, offset, line_bases, line_width)
                name = line[1:].split()[0].decode()
                order.append(name)
                length = 0
                offset = pos + line_len
                line_bases = 0
                line_width = 0
            else:
                if line_bases == 0:
                    line_bases = len(line)
                    line_width = line_len
                length += len(line)
            pos += line_len
        if name is not None:
            entries[name] = _FaiEntry(length, offset, line_bases, line_width)
    try:  # cache the index beside the FASTA; read-only mounts just skip it
        with open(path + ".fai", "wt") as out:
            for n in order:
                e = entries[n]
                out.write(f"{n}\t{e.length}\t{e.offset}\t{e.line_bases}\t{e.line_width}\n")
    except OSError:
        pass
    return entries


def read_fai(path: str) -> dict[str, _FaiEntry]:
    entries: dict[str, _FaiEntry] = {}
    with open(path, "rt") as fh:
        for line in fh:
            p = line.rstrip("\n").split("\t")
            entries[p[0]] = _FaiEntry(int(p[1]), int(p[2]), int(p[3]), int(p[4]))
    return entries


class FastaReader:
    """Random-access FASTA with 0-based half-open ``fetch``."""

    def __init__(self, path: str):
        self.path = path
        fai = path + ".fai"
        if os.path.exists(fai):
            self._index = read_fai(fai)
        else:
            self._index = build_fai(path)
        self._fh = open(path, "rb")
        self._encoded: dict[str, np.ndarray] = {}

    #: byte budget for the encoded-contig cache (default 4 GB covers a
    #: whole human genome; VCTPU_FASTA_CACHE_BYTES tunes it down for
    #: memory-constrained workers — 0 disables caching entirely)
    _ENC_CACHE_BYTES = int(os.environ.get("VCTPU_FASTA_CACHE_BYTES", 4 << 30))

    def fetch_encoded(self, chrom: str) -> np.ndarray:
        """Whole-contig uint8 codes (A0 C1 G2 T3 N4), cached per contig —
        repeated window gathers re-read one array instead of re-decoding
        the FASTA text each time. The cache is byte-bounded (FIFO)."""
        got = self._encoded.get(chrom)
        if got is None:
            got = self._encode_contig(chrom)
            if len(got) <= self._ENC_CACHE_BYTES:
                total = sum(len(v) for v in self._encoded.values()) + len(got)
                while self._encoded and total > self._ENC_CACHE_BYTES:
                    total -= len(self._encoded.pop(next(iter(self._encoded))))
                self._encoded[chrom] = got
        return got

    def _encode_contig(self, chrom: str) -> np.ndarray:
        """Whole-contig encode without the str round-trip: raw bytes ->
        newline strip (vectorized reshape for the common fixed-width
        layout) -> one table lookup. ~5x the decode+replace+upper path at
        chromosome scale — this is the flagship pipeline's first-touch
        cost per contig."""
        e = self._index[chrom]
        if e.length == 0:
            return np.empty(0, dtype=np.uint8)
        last_line = (e.length - 1) // e.line_bases
        byte_end = e.offset + last_line * e.line_width + ((e.length - 1) - last_line * e.line_bases) + 1
        self._fh.seek(e.offset)
        raw = np.frombuffer(self._fh.read(byte_end - e.offset), dtype=np.uint8)
        gap = e.line_width - e.line_bases  # newline bytes per full line
        if gap == 0:
            return _CODE[raw[: e.length]]
        full = len(raw) // e.line_width
        body = _CODE[raw[: full * e.line_width].reshape(full, e.line_width)[:, : e.line_bases]]
        tail = raw[full * e.line_width :]
        if len(tail) == 0:
            return body.reshape(-1)[: e.length]
        return np.concatenate([body.reshape(-1), _CODE[tail[: e.line_bases]]])[: e.length]

    @property
    def references(self) -> list[str]:
        return list(self._index)

    def get_reference_length(self, chrom: str) -> int:
        return self._index[chrom].length

    def fetch(self, chrom: str, start: int, end: int) -> str:
        """Uppercased sequence [start, end), clamped to contig bounds."""
        e = self._index[chrom]
        start = max(0, int(start))
        end = min(e.length, int(end))
        if end <= start:
            return ""
        first_line = start // e.line_bases
        byte_start = e.offset + first_line * e.line_width + (start - first_line * e.line_bases)
        last_line = (end - 1) // e.line_bases
        byte_end = e.offset + last_line * e.line_width + ((end - 1) - last_line * e.line_bases) + 1
        self._fh.seek(byte_start)
        data = self._fh.read(byte_end - byte_start)
        return data.replace(b"\n", b"").replace(b"\r", b"").decode().upper()

    def fetch_array(self, chrom: str, start: int, end: int, pad: str = "N") -> np.ndarray:
        """uint8 sequence codes over [start, end) with out-of-bounds padding.

        Codes: A=0 C=1 G=2 T=3 other=4 — the device-side encoding used by the
        featurization kernels.
        """
        seq = self.fetch(chrom, start, end)
        left_pad = max(0, -start)
        right_pad = (end - start) - left_pad - len(seq)
        return encode_seq(pad * left_pad + seq + pad * right_pad)

    def close(self) -> None:
        self._fh.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


_CODE = np.full(256, 4, dtype=np.uint8)
for _i, _b in enumerate(b"ACGT"):
    _CODE[_b] = _i
for _i, _b in enumerate(b"acgt"):
    _CODE[_b] = _i


def encode_seq(seq: str) -> np.ndarray:
    """str -> uint8 codes (A0 C1 G2 T3 N/other 4)."""
    return _CODE[np.frombuffer(seq.encode(), dtype=np.uint8)]


def decode_seq(codes: np.ndarray) -> str:
    return "".join("ACGTN"[c] for c in codes)


def revcomp(seq: str) -> str:
    """Reverse complement (parity: ugbio_core.dna_sequence_utils.revcomp)."""
    comp = {"A": "T", "C": "G", "G": "C", "T": "A", "N": "N", "a": "t", "c": "g", "g": "c", "t": "a"}
    return "".join(comp.get(c, "N") for c in reversed(seq))
