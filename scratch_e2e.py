"""Scratch: host-side stage breakdown of the e2e filter path (CPU jax)."""
import os, sys, time, tempfile
sys.path.insert(0, "/root/repo")
import numpy as np

import bench

d = tempfile.mkdtemp(prefix="vctpu_e2e_")
t0 = time.perf_counter()
bench.make_fixtures(d)
print("fixtures:", round(time.perf_counter() - t0, 2), flush=True)

from variantcalling_tpu.io.vcf import read_vcf, write_vcf
from variantcalling_tpu.io.fasta import FastaReader
from variantcalling_tpu.featurize import host_featurize, classify_alleles
from variantcalling_tpu.synthetic import synthetic_forest
from variantcalling_tpu.pipelines.filter_variants import filter_variants, fused_featurize_score

t0 = time.perf_counter(); table = read_vcf(os.path.join(d, "calls.vcf")); print("ingest:", round(time.perf_counter() - t0, 2), flush=True)
fasta = FastaReader(os.path.join(d, "ref.fa"))
model = synthetic_forest(np.random.default_rng(0), n_trees=40, depth=6)

t0 = time.perf_counter(); alle = classify_alleles(table); print("classify_alleles:", round(time.perf_counter() - t0, 3), flush=True)
t0 = time.perf_counter()
hf = host_featurize(table, fasta, compute_windows=False)
print("host_featurize:", round(time.perf_counter() - t0, 3), flush=True)

# host cols -> matrix stack cost
import variantcalling_tpu.featurize as fz
host_names = [f for f in hf.names if f not in fz.DEVICE_FEATURES]
t0 = time.perf_counter()
host_feats = np.stack([np.asarray(hf.cols[f], dtype=np.float32) for f in host_names], axis=1)
print("host stack:", round(time.perf_counter() - t0, 3), "shape", host_feats.shape, flush=True)

t0 = time.perf_counter()
blk, off = fz.globalize_positions(table, fz.device_genome(fasta))
print("genome+globalize:", round(time.perf_counter() - t0, 3), flush=True)

# full featurize+score twice (compile then steady)
t0 = time.perf_counter(); filter_variants(table, model, fasta); print("fvs compile:", round(time.perf_counter() - t0, 2), flush=True)
t0 = time.perf_counter(); score, filters = filter_variants(table, model, fasta); print("fvs steady:", round(time.perf_counter() - t0, 2), flush=True)

t0 = time.perf_counter()
table.header.ensure_filter("LOW_SCORE", "x")
table.header.ensure_info("TREE_SCORE", "1", "Float", "score")
write_vcf(os.path.join(d, "out.vcf"), table, new_filters=filters,
          extra_info={"TREE_SCORE": np.round(score, 4)}, verbatim_core=True)
print("writeback:", round(time.perf_counter() - t0, 2), flush=True)
